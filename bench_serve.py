"""Serving-plane benchmark: synthetic heavy multi-tenant traffic.

``bench.py`` measures one huge board; this bench measures the opposite
regime the ROADMAP north-star actually describes — **many small boards for
many users**: N concurrent sessions with mixed rules (life-likes AND
Generations) and mixed sizes, driven through the real ``/boards`` HTTP API
(``akka_game_of_life_tpu/serve/``) by a pool of client threads, all
advancing through vmapped batched device programs.

Reported in BENCH record format (one JSON line each):

- **boards/sec** — step requests sustained end-to-end (HTTP + queue +
  batch), vs the reference's ceiling of one board per 3 s tick;
- **cell-updates/s aggregate** — Σ cells·steps over the wall clock;
- **p50 / p99 step latency** — client-observed, vs the reference's 3 s.

Then two acceptance gates, asserted loudly:

1. **digest-vs-oracle**: a sample of sessions is re-run single-board
   (``ops.stencil.multi_step_fn`` on the same seeded init) and each
   session's served digest must equal its oracle's — a batching plane that
   changes the simulation is not a serving plane;
2. **admission control answers, never wedges**: one create past the
   session cap and one step past the queue bound must return HTTP 429
   (machine-readable reason), while every job already admitted completes
   with no state lost (epochs land exactly where the request count says).

Usage:
  python bench_serve.py                         # 256 sessions (CPU-friendly)
  python bench_serve.py --sessions 1024 --threads 32

Also wired into ``bench_suite.py`` as config 12.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

# The reference's throughput ceiling (BASELINE.md): ONE board, 49 cells,
# one epoch per 3 s tick.  Its serving analogs: 1/3 board-steps/sec and
# 49/3 cell-updates/sec, and 3 s of latency floor per step.
REFERENCE_BOARDS_PER_SEC = 1 / 3.0
REFERENCE_CEILING = 49 / 3.0
REFERENCE_TICK_S = 3.0

DEFAULT_RULES = (
    "conway", "highlife", "seeds", "day-and-night",
    "brians-brain", "star-wars",
)
DEFAULT_SIZES = (16, 24, 32, 48, 64)


def _request(base: str, method: str, path: str, doc=None, timeout=60):
    data = json.dumps(doc).encode("utf-8") if doc is not None else None
    req = urllib.request.Request(base + path, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return float("nan")
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def bench_serve(
    sessions: int = 256,
    steps: int = 8,
    rounds: int = 4,
    threads: int = 16,
    tenants: int = 8,
    sample: int = 16,
    rules=DEFAULT_RULES,
    sizes=DEFAULT_SIZES,
    queue_drill_depth: int = 32,
    emit=print,
) -> dict:
    """Run the traffic + drills; emit BENCH lines; return the summary
    record (the last line emitted)."""
    import jax.numpy as jnp

    from akka_game_of_life_tpu.obs import MetricsServer
    from akka_game_of_life_tpu.obs.catalog import install
    from akka_game_of_life_tpu.obs.metrics import MetricsRegistry
    from akka_game_of_life_tpu.ops import digest as odigest, stencil
    from akka_game_of_life_tpu.runtime.config import SimulationConfig
    from akka_game_of_life_tpu.serve import SessionRouter, board_routes
    from akka_game_of_life_tpu.utils.patterns import random_grid

    config = f"serve-{sessions}"
    cfg = SimulationConfig(
        role="serve",
        serve_max_sessions=sessions,
        # The queue bound is sized to be DRILLABLE (pause the engine, fill
        # it with queue_drill_depth jobs, overflow once) while staying
        # comfortably above the client pool's in-flight ceiling so steady
        # traffic never trips it.
        serve_queue_depth=max(queue_drill_depth, 2 * threads),
        serve_max_steps=max(64, steps),
        flight_dir="",
    )
    registry = install(MetricsRegistry())
    router = SessionRouter(cfg, registry=registry)
    server = MetricsServer(
        registry, port=0, host="127.0.0.1", routes=board_routes(router)
    )
    base = f"http://127.0.0.1:{server.port}"

    # -- create the tenant mix ------------------------------------------------
    specs = []  # (sid, rule, (h, w), seed)
    for i in range(sessions):
        rule = rules[i % len(rules)]
        side = sizes[i % len(sizes)]
        h, w = side, max(1, side - (i % 7))  # non-square mix
        status, doc = _request(
            base, "POST", "/boards",
            {"tenant": f"t{i % tenants}", "rule": rule,
             "height": h, "width": w, "seed": i},
        )
        assert status == 201, f"create {i} failed: {status} {doc}"
        specs.append((doc["id"], rule, (h, w), i))

    # One create past the cap must answer 429 without disturbing anything.
    status, doc = _request(
        base, "POST", "/boards", {"height": 8, "width": 8}
    )
    assert status == 429 and doc.get("reason") == "max_sessions", (
        f"expected 429 max_sessions past the cap, got {status} {doc}"
    )

    # -- sustained traffic: rounds × sessions step requests -------------------
    latencies: list = []
    lat_lock = threading.Lock()
    issued = {sid: 0 for sid, _, _, _ in specs}

    def run_traffic(round_count: int, record: bool) -> float:
        """Drive round_count × sessions step requests through `threads`
        concurrent clients; returns the wall time."""
        work = [
            spec for _ in range(round_count) for spec in specs
        ]  # round-major: every session stays concurrently live throughout
        cursor = {"i": 0}
        cursor_lock = threading.Lock()
        errors: list = []

        def client():
            while True:
                with cursor_lock:
                    i = cursor["i"]
                    if i >= len(work):
                        return
                    cursor["i"] = i + 1
                sid = work[i][0]
                t0 = time.perf_counter()
                status, doc = _request(
                    base, "POST", f"/boards/{sid}/step", {"steps": steps}
                )
                dt = time.perf_counter() - t0
                if status != 200:
                    errors.append((sid, status, doc))
                    return
                with lat_lock:
                    issued[sid] += steps
                    if record:
                        latencies.append(dt)

        t0 = time.perf_counter()
        pool = [threading.Thread(target=client) for _ in range(threads)]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        wall = time.perf_counter() - t0
        assert not errors, f"step traffic failed: {errors[:3]}"
        return wall

    # Warmup round (uncounted): the first ticks pay the jit compiles for
    # this traffic mix's (class, length, batch) buckets — steady-state
    # latency is what the report is about.  The warmed epochs still count
    # toward each session's oracle total via `issued`.
    run_traffic(1, record=False)
    wall = run_traffic(rounds, record=True)
    n_requests = sessions * rounds
    assert len(latencies) == n_requests

    # Timed phase only: every session served exactly `rounds` requests of
    # `steps` epochs inside `wall` (the warmup round is excluded).
    cells_stepped = sum(
        h * w * steps * rounds for _, _, (h, w), _ in specs
    )
    boards_per_sec = n_requests / wall
    cells_per_sec = cells_stepped / wall
    lat = sorted(latencies)
    p50, p99 = _percentile(lat, 0.50), _percentile(lat, 0.99)

    emit(json.dumps({
        "config": config,
        "metric": (
            f"step requests/sec sustained, {sessions} sessions x "
            f"{rounds} rounds x {steps} steps, {len(rules)} rules x "
            f"{len(sizes)} sizes, {threads} HTTP client threads"
        ),
        "value": boards_per_sec,
        "unit": "boards/sec",
        "vs_baseline": boards_per_sec / REFERENCE_BOARDS_PER_SEC,
    }))
    emit(json.dumps({
        "config": config,
        "metric": "cell-updates/sec aggregate across all tenant boards",
        "value": cells_per_sec,
        "unit": "cell-updates/sec",
        "vs_baseline": cells_per_sec / REFERENCE_CEILING,
    }))
    for name, value in (("p50", p50), ("p99", p99)):
        emit(json.dumps({
            "config": config,
            "metric": f"{name} step-request latency, client-observed "
            f"(HTTP + queue + batched device program)",
            "value": value,
            "unit": "seconds",
            "vs_baseline": value / REFERENCE_TICK_S,
        }))

    # -- queue backpressure drill --------------------------------------------
    # Freeze the engine, fill the queue exactly to its bound, overflow once
    # (the deterministic 429), thaw, and require every admitted job to land
    # — backpressure sheds NEW load, it never drops admitted state.
    router.pause()
    depth = router.queue_depth
    # Cycle over sessions so the drill fills the queue even when the bound
    # exceeds the session count (same-session jobs queue fine — the engine
    # serializes them one per tick).
    drilled = [specs[i % len(specs)] for i in range(depth)]
    drill_results: list = []

    def drill_step(sid):
        drill_results.append(
            _request(base, "POST", f"/boards/{sid}/step", {"steps": 1})
        )

    drill_pool = [
        threading.Thread(target=drill_step, args=(sid,))
        for sid, _, _, _ in drilled
    ]
    for t in drill_pool:
        t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if router.stats()["queue_depth"] >= depth:
            break
        time.sleep(0.01)
    assert router.stats()["queue_depth"] >= depth, "drill queue never filled"
    status, doc = _request(
        base, "POST", f"/boards/{specs[0][0]}/step", {"steps": 1}
    )
    assert status == 429 and doc.get("reason") == "queue_full", (
        f"expected 429 queue_full past the bound, got {status} {doc}"
    )
    router.resume()
    for t in drill_pool:
        t.join()
    assert all(s == 200 for s, _ in drill_results), (
        f"admitted jobs must complete through backpressure: "
        f"{[r for r in drill_results if r[0] != 200][:3]}"
    )
    for sid, _, _, _ in drilled:
        issued[sid] += 1

    # -- digest-vs-oracle certification ---------------------------------------
    stride = max(1, len(specs) // max(1, sample))
    sampled = specs[::stride][:sample]
    mismatches = []
    for sid, rule, (h, w), seed in sampled:
        status, doc = _request(base, "GET", f"/boards/{sid}")
        assert status == 200, (sid, status)
        assert doc["epoch"] == issued[sid], (
            f"{sid}: epoch {doc['epoch']} != issued {issued[sid]} — "
            f"state lost"
        )
        board0 = random_grid((h, w), density=0.5, seed=seed)
        oracle = np.asarray(
            stencil.multi_step_fn(rule, issued[sid])(jnp.asarray(board0))
        )
        want = odigest.format_digest(
            odigest.value(odigest.digest_dense_np(oracle))
        )
        if doc["digest"] != want:
            mismatches.append((sid, rule, doc["digest"], want))
    assert not mismatches, f"digest mismatches vs oracle: {mismatches[:3]}"

    snap = registry.snapshot()
    record = {
        "config": config,
        "metric": "serving-plane summary",
        "value": boards_per_sec,
        "unit": "boards/sec",
        "vs_baseline": boards_per_sec / REFERENCE_BOARDS_PER_SEC,
        "sessions": sessions,
        "rounds": rounds,
        "steps_per_request": steps,
        "threads": threads,
        "tenants": tenants,
        "boards_per_sec": boards_per_sec,
        "cells_per_sec": cells_per_sec,
        "p50_s": p50,
        "p99_s": p99,
        "rejected_create_429": 1,
        "rejected_step_429": 1,
        "digest_ok": True,
        "sampled": len(sampled),
        "metrics": {
            k: v for k, v in snap.items() if k.startswith("gol_serve")
        },
    }
    emit(json.dumps(record))
    server.close()
    router.close()
    return record


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sessions", type=int, default=256)
    parser.add_argument("--steps", type=int, default=8,
                        help="generations per step request")
    parser.add_argument("--rounds", type=int, default=4,
                        help="step requests per session")
    parser.add_argument("--threads", type=int, default=16)
    parser.add_argument("--tenants", type=int, default=8)
    parser.add_argument("--sample", type=int, default=16,
                        help="sessions digest-certified against the oracle")
    parser.add_argument("--sizes", default=",".join(map(str, DEFAULT_SIZES)))
    parser.add_argument("--rules", default=",".join(DEFAULT_RULES))
    parser.add_argument("--platform", default=None)
    args = parser.parse_args()

    from akka_game_of_life_tpu.cli import _apply_platform

    _apply_platform(args.platform)
    bench_serve(
        sessions=args.sessions,
        steps=args.steps,
        rounds=args.rounds,
        threads=args.threads,
        tenants=args.tenants,
        sample=args.sample,
        rules=tuple(args.rules.split(",")),
        sizes=tuple(int(v) for v in args.sizes.split(",")),
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
