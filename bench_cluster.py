"""Cluster data-plane throughput benchmark: the halo wire plane, A/B.

``bench.py`` measures the compute side (the Mosaic stencil); this bench
measures the side that bounds the cluster at scale — the worker↔worker
boundary-ring exchange (Casper's framing: stencil performance is a
data-movement problem, bytes moved per updated cell).  It runs the SAME
seeded multi-worker loopback cluster twice:

  A. ``raw``     — ring_pack=off, ring_batch=off: one frame per ring, dense
                   uint8 payloads (the reference's per-message wire shape);
  B. ``packed``  — ring_pack=on, ring_batch=on: 32 cells/uint32 word on the
                   wire, all rings for one peer per epoch coalesced into one
                   PEER_RING_BATCH frame, sent from the per-peer async lane.

and reports, in the BENCH record format (one JSON line each): aggregate
cell-updates/sec, peer-plane frames/epoch, and wire bytes/epoch per
variant, then the A/B reduction ratios.  Both runs certify their final
state against the dense single-process oracle via the 64-bit digest plane
(``ops/digest.py``): each worker digests its tiles locally, the frontend
merges the lanes in O(tiles) bytes, and the merged value must equal the
oracle board's digest — a wire-format optimization that changes the
simulation is not an optimization.  At ≤ 1024² the full boards are
ADDITIONALLY compared bit-for-bit, which is the digest's own oracle;
above that the digest IS the certification and no board is ever
assembled or fetched.

Usage:
  python bench_cluster.py                    # defaults (CPU-friendly)
  python bench_cluster.py --size 2048 --epochs 64 --engine jax

Also wired into ``bench_suite.py`` as config 9.
"""

from __future__ import annotations

import argparse
import io
import json
import time

import numpy as np

# The reference's throughput ceiling (cells/tick at its 6x6 default on a
# 3 s tick — BASELINE.md), the baseline every cluster line compares to.
REFERENCE_CEILING = 49 / 3.0


def _oracle(cfg, epochs):
    import jax.numpy as jnp

    from akka_game_of_life_tpu.models import get_model
    from akka_game_of_life_tpu.runtime.simulation import initial_board

    return np.asarray(
        get_model(cfg.rule).run(epochs)(jnp.asarray(initial_board(cfg)))
    )


def _run_variant(
    *, size, epochs, workers, tiles_per_worker, exchange_width, engine,
    ring_pack, ring_batch,
):
    from akka_game_of_life_tpu.obs.catalog import install
    from akka_game_of_life_tpu.obs.metrics import MetricsRegistry
    from akka_game_of_life_tpu.runtime.config import SimulationConfig
    from akka_game_of_life_tpu.runtime.harness import cluster
    from akka_game_of_life_tpu.runtime.render import BoardObserver

    cfg = SimulationConfig(
        height=size, width=size, seed=0, max_epochs=epochs,
        exchange_width=exchange_width, tiles_per_worker=tiles_per_worker,
        ring_pack=ring_pack, ring_batch=ring_batch, flight_dir="",
        obs_digest=True,
    )
    registry = install(MetricsRegistry())
    t0 = time.perf_counter()
    with cluster(
        cfg, workers, observer=BoardObserver(out=io.StringIO()),
        engine=engine, registry=registry,
    ) as h:
        final = h.run_to_completion(timeout=1200)
        final_digest = h.frontend.final_digest
    dt = time.perf_counter() - t0
    snap = registry.snapshot()
    return cfg, final, final_digest, dt, {
        # Peer data-plane frames (ring/batch frames + pull asks + hellos)
        # and the bytes that actually hit the wire, per simulated epoch.
        "frames_per_epoch": snap.get("gol_peer_sends_total", 0.0) / epochs,
        "wire_bytes_per_epoch": (
            snap.get("gol_ring_packed_bytes_total", 0.0) / epochs
        ),
        "dense_bytes_per_epoch": (
            snap.get("gol_ring_bytes_total", 0.0) / epochs
        ),
        "rings_per_frame": (
            snap["gol_ring_batch_size"]["sum"]
            / snap["gol_ring_batch_size"]["count"]
            if snap.get("gol_ring_batch_size", {}).get("count")
            else 1.0
        ),
        "cells_per_sec": size * size * epochs / dt,
        "metrics": {
            k: v
            for k, v in snap.items()
            if k.startswith(("gol_peer", "gol_ring"))
        },
    }


def bench_cluster_halo(
    size: int = 1024,
    epochs: int = 32,
    workers: int = 2,
    # 8 tiles/worker gives the coalescer a full batch per peer per epoch:
    # measured ~3.7x frames/epoch and 8.0x wire-bytes/epoch reduction at
    # the defaults on this host (4 tiles/worker hovers near 2.0x because
    # pull-ask frames — equal in both variants — dilute the ratio).
    tiles_per_worker: int = 8,
    exchange_width: int = 4,
    engine: str = "numpy",
    emit=print,
) -> dict:
    """Run the A/B and emit BENCH-format JSON lines; returns the summary
    record (the last line emitted)."""
    config = f"cluster-halo-{size}"
    stats = {}
    finals = {}
    digests = {}
    for label, pack, batch in (("raw", False, False), ("packed", True, True)):
        cfg, final, final_digest, dt, s = _run_variant(
            size=size, epochs=epochs, workers=workers,
            tiles_per_worker=tiles_per_worker,
            exchange_width=exchange_width, engine=engine,
            ring_pack=pack, ring_batch=batch,
        )
        stats[label], finals[label] = s, final
        digests[label] = final_digest
        emit(
            json.dumps(
                {
                    "config": config,
                    "metric": (
                        f"cell-updates/sec aggregate, conway {size}x{size} "
                        f"TCP cluster ({workers} workers x "
                        f"{tiles_per_worker} tiles, {engine} engine, "
                        f"exchange_width={exchange_width}, halo wire="
                        f"{label})"
                    ),
                    "value": s["cells_per_sec"],
                    "unit": "cell-updates/sec",
                    "vs_baseline": s["cells_per_sec"] / REFERENCE_CEILING,
                    "frames_per_epoch": s["frames_per_epoch"],
                    "wire_bytes_per_epoch": s["wire_bytes_per_epoch"],
                    "dense_bytes_per_epoch": s["dense_bytes_per_epoch"],
                    "rings_per_frame": s["rings_per_frame"],
                    "metrics": s["metrics"],
                },
            ),
            flush=True,
        )

    from akka_game_of_life_tpu.ops import digest as odigest

    # Certification is digest-first: merged per-tile digests (O(tiles)
    # bytes through the control plane) against the dense oracle's digest.
    # Full-board comparison is retained only at ≤ 1024², where it serves
    # as the digest's own oracle — above that nothing assembles a board.
    oracle = _oracle(cfg, epochs)
    oracle_digest = odigest.value(odigest.digest_dense_np(oracle))
    digest_ok = all(d == oracle_digest for d in digests.values())
    oracle_ok = None
    if size <= 1024:
        oracle_ok = all(np.array_equal(f, oracle) for f in finals.values())

    def _ratio(a: float, b: float):
        # A single-worker run has no remote peer traffic at all: report
        # null ratios (with the fields still present) instead of dying on
        # a ZeroDivisionError after both simulations already ran.
        return a / b if b else None

    byte_ratio = _ratio(
        stats["raw"]["wire_bytes_per_epoch"],
        stats["packed"]["wire_bytes_per_epoch"],
    )
    frame_ratio = _ratio(
        stats["raw"]["frames_per_epoch"],
        stats["packed"]["frames_per_epoch"],
    )
    summary = {
        "config": config,
        "metric": (
            "halo wire A/B: raw / packed+batched reduction "
            "(bytes x, frames x)"
        ),
        "value": byte_ratio,
        "unit": "x",
        "vs_baseline": byte_ratio,
        "wire_bytes_reduction": byte_ratio,
        "frames_reduction": frame_ratio,
        "digest_certified": digest_ok,
        "final_digest": odigest.format_digest(oracle_digest),
        # Bit-for-bit board comparison only at ≤ 1024² (the digest's own
        # oracle); null above — the digest is the certification there.
        "oracle_bit_identical": oracle_ok,
    }
    emit(json.dumps(summary), flush=True)
    if not digest_ok:
        got = {
            k: odigest.format_digest(v) if v is not None else None
            for k, v in digests.items()
        }
        raise AssertionError(
            f"{config}: a variant's merged final digest diverged from the "
            f"dense oracle's ({got} vs "
            f"{odigest.format_digest(oracle_digest)}) — the wire plane is "
            f"corrupting the simulation"
        )
    if oracle_ok is False:
        raise AssertionError(
            f"{config}: digests matched but the boards differ — the digest "
            f"plane itself is broken (collision or layout bug)"
        )
    return summary


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=1024)
    parser.add_argument("--epochs", type=int, default=32)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--tiles-per-worker", type=int, default=8)
    parser.add_argument("--exchange-width", type=int, default=4)
    parser.add_argument(
        "--engine", choices=["numpy", "jax", "swar"], default="numpy",
        help="worker tile engine (numpy = portable default; the wire "
        "plane under test is engine-independent)",
    )
    parser.add_argument(
        "--platform", default=None, help="pin jax platform (e.g. cpu)"
    )
    args = parser.parse_args()

    from akka_game_of_life_tpu.cli import _apply_platform

    _apply_platform(args.platform)
    bench_cluster_halo(
        size=args.size,
        epochs=args.epochs,
        workers=args.workers,
        tiles_per_worker=args.tiles_per_worker,
        exchange_width=args.exchange_width,
        engine=args.engine,
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
