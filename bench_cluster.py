"""Cluster data-plane throughput benchmark: the halo wire plane, A/B.

``bench.py`` measures the compute side (the Mosaic stencil); this bench
measures the side that bounds the cluster at scale — the worker↔worker
boundary-ring exchange (Casper's framing: stencil performance is a
data-movement problem, bytes moved per updated cell).  It runs the SAME
seeded multi-worker loopback cluster twice:

  A. ``raw``     — ring_pack=off, ring_batch=off: one frame per ring, dense
                   uint8 payloads (the reference's per-message wire shape);
  B. ``packed``  — ring_pack=on, ring_batch=on: 32 cells/uint32 word on the
                   wire, all rings for one peer per epoch coalesced into one
                   PEER_RING_BATCH frame, sent from the per-peer async lane.

and reports, in the BENCH record format (one JSON line each): aggregate
cell-updates/sec, peer-plane frames/epoch, and wire bytes/epoch per
variant, then the A/B reduction ratios.  Both runs certify their final
state against the dense single-process oracle via the 64-bit digest plane
(``ops/digest.py``): each worker digests its tiles locally, the frontend
merges the lanes in O(tiles) bytes, and the merged value must equal the
oracle board's digest — a wire-format optimization that changes the
simulation is not an optimization.  At ≤ 1024² the full boards are
ADDITIONALLY compared bit-for-bit, which is the digest's own oracle;
above that the digest IS the certification and no board is ever
assembled or fetched.

Usage:
  python bench_cluster.py                    # defaults (CPU-friendly)
  python bench_cluster.py --size 2048 --epochs 64 --engine jax

Also wired into ``bench_suite.py`` as config 9.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import time

import numpy as np

# The reference's throughput ceiling (cells/tick at its 6x6 default on a
# 3 s tick — BASELINE.md), the baseline every cluster line compares to.
REFERENCE_CEILING = 49 / 3.0


def _oracle(cfg, epochs):
    import jax.numpy as jnp

    from akka_game_of_life_tpu.models import get_model
    from akka_game_of_life_tpu.runtime.simulation import initial_board

    return np.asarray(
        get_model(cfg.rule).run(epochs)(jnp.asarray(initial_board(cfg)))
    )


def _run_variant(
    *, size, epochs, workers, tiles_per_worker, exchange_width, engine,
    ring_pack, ring_batch, pattern=None, sparse_cluster=False,
):
    from akka_game_of_life_tpu.obs.catalog import install
    from akka_game_of_life_tpu.obs.metrics import MetricsRegistry
    from akka_game_of_life_tpu.runtime.config import SimulationConfig
    from akka_game_of_life_tpu.runtime.harness import cluster
    from akka_game_of_life_tpu.runtime.render import BoardObserver

    cfg = SimulationConfig(
        height=size, width=size, seed=0, max_epochs=epochs,
        exchange_width=exchange_width, tiles_per_worker=tiles_per_worker,
        ring_pack=ring_pack, ring_batch=ring_batch, flight_dir="",
        obs_digest=True, pattern=pattern, sparse_cluster=sparse_cluster,
    )
    registry = install(MetricsRegistry())
    t0 = time.perf_counter()
    with cluster(
        cfg, workers, observer=BoardObserver(out=io.StringIO()),
        engine=engine, registry=registry,
    ) as h:
        final = h.run_to_completion(timeout=1200)
        final_digest = h.frontend.final_digest
    dt = time.perf_counter() - t0
    snap = registry.snapshot()
    return cfg, final, final_digest, dt, {
        # Peer data-plane frames (ring/batch frames + pull asks + hellos)
        # and the bytes that actually hit the wire, per simulated epoch.
        "tiles_skipped": snap.get("gol_tiles_skipped_total", 0.0),
        "same_markers": snap.get("gol_ring_same_markers_total", 0.0),
        "frames_per_epoch": snap.get("gol_peer_sends_total", 0.0) / epochs,
        "wire_bytes_per_epoch": (
            snap.get("gol_ring_packed_bytes_total", 0.0) / epochs
        ),
        "dense_bytes_per_epoch": (
            snap.get("gol_ring_bytes_total", 0.0) / epochs
        ),
        "rings_per_frame": (
            snap["gol_ring_batch_size"]["sum"]
            / snap["gol_ring_batch_size"]["count"]
            if snap.get("gol_ring_batch_size", {}).get("count")
            else 1.0
        ),
        "cells_per_sec": size * size * epochs / dt,
        "metrics": {
            k: v
            for k, v in snap.items()
            if k.startswith(("gol_peer", "gol_ring"))
        },
    }


def bench_cluster_halo(
    size: int = 1024,
    epochs: int = 32,
    workers: int = 2,
    # 8 tiles/worker gives the coalescer a full batch per peer per epoch:
    # measured ~3.7x frames/epoch and 8.0x wire-bytes/epoch reduction at
    # the defaults on this host (4 tiles/worker hovers near 2.0x because
    # pull-ask frames — equal in both variants — dilute the ratio).
    tiles_per_worker: int = 8,
    exchange_width: int = 4,
    engine: str = "numpy",
    emit=print,
) -> dict:
    """Run the A/B and emit BENCH-format JSON lines; returns the summary
    record (the last line emitted)."""
    config = f"cluster-halo-{size}"
    stats = {}
    finals = {}
    digests = {}
    for label, pack, batch in (("raw", False, False), ("packed", True, True)):
        cfg, final, final_digest, dt, s = _run_variant(
            size=size, epochs=epochs, workers=workers,
            tiles_per_worker=tiles_per_worker,
            exchange_width=exchange_width, engine=engine,
            ring_pack=pack, ring_batch=batch,
        )
        stats[label], finals[label] = s, final
        digests[label] = final_digest
        emit(
            json.dumps(
                {
                    "config": config,
                    "metric": (
                        f"cell-updates/sec aggregate, conway {size}x{size} "
                        f"TCP cluster ({workers} workers x "
                        f"{tiles_per_worker} tiles, {engine} engine, "
                        f"exchange_width={exchange_width}, halo wire="
                        f"{label})"
                    ),
                    "value": s["cells_per_sec"],
                    "unit": "cell-updates/sec",
                    "vs_baseline": s["cells_per_sec"] / REFERENCE_CEILING,
                    "frames_per_epoch": s["frames_per_epoch"],
                    "wire_bytes_per_epoch": s["wire_bytes_per_epoch"],
                    "dense_bytes_per_epoch": s["dense_bytes_per_epoch"],
                    "rings_per_frame": s["rings_per_frame"],
                    "metrics": s["metrics"],
                },
            ),
            flush=True,
        )

    from akka_game_of_life_tpu.ops import digest as odigest

    # Certification is digest-first: merged per-tile digests (O(tiles)
    # bytes through the control plane) against the dense oracle's digest.
    # Full-board comparison is retained only at ≤ 1024², where it serves
    # as the digest's own oracle — above that nothing assembles a board.
    oracle = _oracle(cfg, epochs)
    oracle_digest = odigest.value(odigest.digest_dense_np(oracle))
    digest_ok = all(d == oracle_digest for d in digests.values())
    oracle_ok = None
    if size <= 1024:
        oracle_ok = all(np.array_equal(f, oracle) for f in finals.values())

    def _ratio(a: float, b: float):
        # A single-worker run has no remote peer traffic at all: report
        # null ratios (with the fields still present) instead of dying on
        # a ZeroDivisionError after both simulations already ran.
        return a / b if b else None

    byte_ratio = _ratio(
        stats["raw"]["wire_bytes_per_epoch"],
        stats["packed"]["wire_bytes_per_epoch"],
    )
    frame_ratio = _ratio(
        stats["raw"]["frames_per_epoch"],
        stats["packed"]["frames_per_epoch"],
    )
    summary = {
        "config": config,
        "metric": (
            "halo wire A/B: raw / packed+batched reduction "
            "(bytes x, frames x)"
        ),
        "value": byte_ratio,
        "unit": "x",
        "vs_baseline": byte_ratio,
        "wire_bytes_reduction": byte_ratio,
        "frames_reduction": frame_ratio,
        "digest_certified": digest_ok,
        "final_digest": odigest.format_digest(oracle_digest),
        # Bit-for-bit board comparison only at ≤ 1024² (the digest's own
        # oracle); null above — the digest is the certification there.
        "oracle_bit_identical": oracle_ok,
    }
    emit(json.dumps(summary), flush=True)
    if not digest_ok:
        got = {
            k: odigest.format_digest(v) if v is not None else None
            for k, v in digests.items()
        }
        raise AssertionError(
            f"{config}: a variant's merged final digest diverged from the "
            f"dense oracle's ({got} vs "
            f"{odigest.format_digest(oracle_digest)}) — the wire plane is "
            f"corrupting the simulation"
        )
    if oracle_ok is False:
        raise AssertionError(
            f"{config}: digests matched but the boards differ — the digest "
            f"plane itself is broken (collision or layout bug)"
        )
    return summary


def bench_cluster_sparse(
    size: int = 1024,
    epochs: int = 64,
    workers: int = 2,
    tiles_per_worker: int = 4,
    exchange_width: int = 4,
    engine: str = "numpy",
    pattern: str = "glider",
    emit=print,
) -> dict:
    """Dilute-universe A/B (docs/OPERATIONS.md "Activity-gated sparse
    stepping"): the SAME seeded pattern board (a glider on an otherwise
    dead ``size``² torus) run with ``sparse_cluster`` off then on.

    Off, every tile does O(area) work per chunk; on, tiles whose state and
    halo repeat skip their compute, publish O(1)-byte same-ring markers,
    and suppress per-chunk pings — throughput goes from O(area) toward
    O(activity).  Both runs certify their merged final digest against the
    dense oracle (a gating plane that changes the simulation is not an
    optimization), and the sparse run must actually have skipped
    (``gol_tiles_skipped_total`` > 0) or the record raises."""
    config = f"cluster-sparse-{size}"
    stats = {}
    digests = {}
    for label, sparse in (("sparse-off", False), ("sparse-on", True)):
        cfg, final, final_digest, dt, s = _run_variant(
            size=size, epochs=epochs, workers=workers,
            tiles_per_worker=tiles_per_worker,
            exchange_width=exchange_width, engine=engine,
            ring_pack=True, ring_batch=True,
            pattern=pattern, sparse_cluster=sparse,
        )
        stats[label] = s
        digests[label] = final_digest
        emit(
            json.dumps(
                {
                    "config": config,
                    "metric": (
                        f"wall-clock epochs/sec, conway {size}x{size} dilute "
                        f"({pattern}) TCP cluster ({workers} workers x "
                        f"{tiles_per_worker} tiles, {engine} engine, "
                        f"exchange_width={exchange_width}, {label})"
                    ),
                    "value": s["cells_per_sec"] / (size * size),
                    "unit": "epochs/sec",
                    "vs_baseline": s["cells_per_sec"] / REFERENCE_CEILING,
                    "cells_per_sec": s["cells_per_sec"],
                    "tiles_skipped": s["tiles_skipped"],
                    "same_markers": s["same_markers"],
                    "wire_bytes_per_epoch": s["wire_bytes_per_epoch"],
                },
            ),
            flush=True,
        )

    from akka_game_of_life_tpu.ops import digest as odigest

    oracle = _oracle(cfg, epochs)
    oracle_digest = odigest.value(odigest.digest_dense_np(oracle))
    digest_ok = all(d == oracle_digest for d in digests.values())
    speedup = (
        stats["sparse-on"]["cells_per_sec"]
        / stats["sparse-off"]["cells_per_sec"]
    )
    summary = {
        "config": config,
        "metric": "dilute-board sparse-on / sparse-off epochs/s speedup",
        "value": speedup,
        "unit": "x",
        "vs_baseline": speedup,
        "tiles_skipped": stats["sparse-on"]["tiles_skipped"],
        "wire_bytes_reduction": (
            stats["sparse-off"]["wire_bytes_per_epoch"]
            / stats["sparse-on"]["wire_bytes_per_epoch"]
            if stats["sparse-on"]["wire_bytes_per_epoch"]
            else None
        ),
        "digest_certified": digest_ok,
        "final_digest": odigest.format_digest(oracle_digest),
    }
    emit(json.dumps(summary), flush=True)
    if not digest_ok:
        got = {
            k: odigest.format_digest(v) if v is not None else None
            for k, v in digests.items()
        }
        raise AssertionError(
            f"{config}: a variant's merged final digest diverged from the "
            f"dense oracle's ({got} vs "
            f"{odigest.format_digest(oracle_digest)}) — the quiescence "
            f"plane is corrupting the simulation"
        )
    if not stats["sparse-on"]["tiles_skipped"]:
        raise AssertionError(
            f"{config}: sparse-on run skipped zero tile chunks — the "
            f"quiescence tier never engaged on a dilute board"
        )
    return summary


def bench_cluster_tsweep(
    size: int = 1024,
    epochs: int = 64,
    workers: int = 2,
    widths=(1, 2, 4, 8),
    tiles_per_worker: int = 4,
    engine: str = "numpy",
    emit=print,
) -> dict:
    """Temporal-blocking T-sweep (ROADMAP item 3's standing record): the
    same seeded cluster run at each ``exchange_width`` T — one peer
    exchange buys T local epochs — reporting aggregate cell-updates/s per
    T and certifying every T's merged final digest against T=1's AND the
    dense oracle's (the Linear Acceleration Theorem legality check, made
    executable)."""
    from akka_game_of_life_tpu.ops import digest as odigest

    config = f"cluster-tsweep-{size}"
    rates = {}
    digests = {}
    cfg = None
    for t in widths:
        cfg, final, final_digest, dt, s = _run_variant(
            size=size, epochs=epochs, workers=workers,
            tiles_per_worker=tiles_per_worker, exchange_width=t,
            engine=engine, ring_pack=True, ring_batch=True,
        )
        rates[t] = s["cells_per_sec"]
        digests[t] = final_digest
        emit(
            json.dumps(
                {
                    "config": config,
                    "metric": (
                        f"cell-updates/sec aggregate, conway {size}x{size} "
                        f"TCP cluster ({workers} workers x "
                        f"{tiles_per_worker} tiles, {engine} engine, "
                        f"exchange_width={t})"
                    ),
                    "value": s["cells_per_sec"],
                    "unit": "cell-updates/sec",
                    "vs_baseline": s["cells_per_sec"] / REFERENCE_CEILING,
                    "exchange_width": t,
                    "frames_per_epoch": s["frames_per_epoch"],
                    "wire_bytes_per_epoch": s["wire_bytes_per_epoch"],
                },
            ),
            flush=True,
        )
    oracle_digest = odigest.value(odigest.digest_dense_np(_oracle(cfg, epochs)))
    digest_ok = all(d == oracle_digest for d in digests.values())
    base = widths[0]
    best = max(rates, key=rates.get)
    summary = {
        "config": config,
        "metric": (
            f"exchange-width sweep T={list(widths)}: best-T / T={base} "
            f"throughput ratio"
        ),
        "value": rates[best] / rates[base],
        "unit": "x",
        "vs_baseline": rates[best] / rates[base],
        "best_width": best,
        "rates": {str(t): r for t, r in rates.items()},
        "digest_certified": digest_ok,
        "final_digest": odigest.format_digest(oracle_digest),
    }
    emit(json.dumps(summary), flush=True)
    if not digest_ok:
        got = {
            str(t): odigest.format_digest(v) if v is not None else None
            for t, v in digests.items()
        }
        raise AssertionError(
            f"{config}: a width's merged final digest diverged from the "
            f"dense oracle's ({got} vs "
            f"{odigest.format_digest(oracle_digest)}) — temporal blocking "
            f"is corrupting the simulation"
        )
    return summary


def bench_cluster_elastic(
    size: int = 1024,
    epochs: int = 96,
    workers: int = 2,
    grow_to: int = 4,
    grow_at: int = None,
    drain_at: int = None,
    tiles_per_worker: int = 4,
    exchange_width: int = 4,
    engine: str = "numpy",
    chaos: bool = False,
    emit=print,
) -> dict:
    """Elastic-cluster drill (docs/OPERATIONS.md "Elastic rebalancing").

    ``--grow-at E``: run a seeded ``workers``→``grow_to`` scale-out — once
    the epoch floor crosses E, the extra workers join mid-run, the
    rebalancer live-migrates tiles onto them, and the record reports
    aggregate cell-updates/s BEFORE vs AFTER the grow (the after window
    includes the migration cost — the honest number).  ``--drain-at E``:
    gracefully drain one loaded worker mid-run (optionally under ``chaos``:
    5% peer-plane drops plus one scheduled partition), asserting zero
    node-loss redeploys.  Both certify the final state against the dense
    oracle via the merged digest plane, like the halo A/B.

    Interpretation: the scale-out raises aggregate throughput when the
    machine has idle cores for the joiners (the record carries ``cores``);
    on a host where the initial workers already saturate the CPU, the
    after-window honestly reports the added wire+migration overhead
    instead.  ``workers`` must be >= 2: a fully-local single worker steps
    synchronously on its dispatch thread and starves the control plane."""
    import threading

    from akka_game_of_life_tpu.obs.catalog import install
    from akka_game_of_life_tpu.obs.metrics import MetricsRegistry
    from akka_game_of_life_tpu.ops import digest as odigest
    from akka_game_of_life_tpu.runtime.config import (
        NetworkChaosConfig,
        SimulationConfig,
    )
    from akka_game_of_life_tpu.runtime.harness import cluster
    from akka_game_of_life_tpu.runtime.render import BoardObserver

    if workers < 2:
        raise SystemExit("elastic drill needs --workers >= 2 (see docstring)")
    config = f"cluster-elastic-{size}"
    cfg = SimulationConfig(
        height=size, width=size, seed=0, max_epochs=epochs,
        exchange_width=exchange_width, tiles_per_worker=tiles_per_worker,
        flight_dir="", obs_digest=True,
        rebalance_enabled=True, rebalance_interval_s=0.05,
        # Large CPU tiles hold the GIL long enough to starve heartbeat
        # threads; the reference's aggressive 1 s auto-down is calibrated
        # for 6x6 boards (same rationale as the scale recovery tests).
        failure_timeout_s=10.0,
        net_chaos=(
            NetworkChaosConfig(
                enabled=True, seed=7, drop_p=0.05, scope="peer",
                partition_after_s=1.0, partition_every_s=120.0,
                partition_heal_s=1.0, max_partitions=1,
            )
            if chaos
            else NetworkChaosConfig()
        ),
    )
    registry = install(MetricsRegistry())
    marks = {}
    drained = {}

    def floor(h):
        return min(h.frontend.tile_epochs.values(), default=0)

    t0 = time.perf_counter()
    with cluster(
        cfg, workers, observer=BoardObserver(out=io.StringIO()),
        engine=engine, registry=registry,
    ) as h:
        h.frontend.wait_for_backends(timeout=10)
        h.frontend.start_simulation()

        def driver():
            # Any escape is recorded, not swallowed: a daemon thread dying
            # silently would skip the drill and let the bench report a
            # drain/grow it never performed.
            try:
                grew = drained_done = False
                while not h.frontend.done.is_set():
                    f = floor(h)
                    if grow_at is not None and not grew and f >= grow_at:
                        marks["grow_t"] = time.perf_counter()
                        marks["grow_epoch"] = f
                        for i in range(grow_to - workers):
                            h.add_worker(f"grown-{i}")
                        grew = True
                    if drain_at is not None and not drained_done and f >= drain_at:
                        loaded = [w for w in h.workers if w.tiles]
                        if not loaded:
                            raise AssertionError(
                                "no worker holds tiles at the drain mark"
                            )
                        victim = loaded[0]
                        drained[victim.name] = h.drain_worker(victim)
                        drained_done = True
                    time.sleep(0.005)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                marks["driver_error"] = e

        t = threading.Thread(target=driver, daemon=True)
        t.start()
        assert h.frontend.done.wait(1200), "elastic drill did not finish"
        assert h.frontend.error is None, h.frontend.error
        if "driver_error" in marks:
            raise AssertionError(
                f"{config}: drill driver died: {marks['driver_error']!r}"
            )
        t_end = time.perf_counter()
        final_digest = h.frontend.final_digest

    snap = registry.snapshot()
    oracle_digest = odigest.value(odigest.digest_dense_np(_oracle(cfg, epochs)))
    digest_ok = final_digest == oracle_digest
    summary = {
        "config": config,
        "cores": os.cpu_count(),
        "metric": (
            f"elastic drill, conway {size}x{size} TCP cluster "
            f"({workers} workers x {tiles_per_worker} tiles, {engine} "
            f"engine" + (", netchaos armed" if chaos else "") + ")"
        ),
        "unit": "cell-updates/sec",
        "migrations": snap.get("gol_migrations_total", 0.0),
        "migration_aborts": snap.get("gol_migration_aborts_total", 0.0),
        "redeploys": snap.get("gol_redeploys_total", 0.0),
        "digest_certified": digest_ok,
        # Both digests on record: on divergence the post-mortem needs the
        # OBSERVED value, not only the expected one.
        "final_digest": (
            odigest.format_digest(final_digest)
            if final_digest is not None
            else None
        ),
        "oracle_digest": odigest.format_digest(oracle_digest),
    }
    # A drill that never fired (the run outpaced its epoch mark, or the
    # driver died before reaching it) must fail, not silently pass with
    # its assertions skipped.
    if grow_at is not None and "grow_t" not in marks:
        raise AssertionError(
            f"{config}: --grow-at {grow_at} never fired (run finished first)"
        )
    if drain_at is not None and not drained:
        raise AssertionError(
            f"{config}: --drain-at {drain_at} never fired (run finished first)"
        )
    if "grow_t" in marks:
        ge = marks["grow_epoch"]
        before = size * size * ge / (marks["grow_t"] - t0)
        after = size * size * (epochs - ge) / (t_end - marks["grow_t"])
        summary.update(
            value=after,
            vs_baseline=after / REFERENCE_CEILING,
            grow_epoch=ge,
            cells_per_sec_before=before,
            cells_per_sec_after=after,
            scale_out_speedup=after / before if before else None,
            workers_after=grow_to,
        )
    else:
        rate = size * size * epochs / (t_end - t0)
        summary.update(value=rate, vs_baseline=rate / REFERENCE_CEILING)
    if drained:
        summary["drained"] = drained  # worker name -> stopped_reason
        summary["drains_completed"] = snap.get("gol_drains_total", 0.0)
    emit(json.dumps(summary), flush=True)
    if not digest_ok:
        raise AssertionError(
            f"{config}: merged final digest diverged from the dense "
            f"oracle's — the elastic plane corrupted the simulation"
        )
    if drained and any(r != "drained" for r in drained.values()):
        raise AssertionError(f"{config}: drain did not complete: {drained}")
    if drained and summary["redeploys"]:
        raise AssertionError(
            f"{config}: drain tripped {summary['redeploys']:.0f} node-loss "
            f"redeploy(s) — the graceful-drain guarantee is broken"
        )
    return summary


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=1024)
    # None = per-drill default (32 for the halo A/B, 96 for the elastic
    # drill; 8 and 4 tiles/worker respectively) — a sentinel, so explicit
    # values equal to a default are honored, not rewritten.
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--tiles-per-worker", type=int, default=None)
    parser.add_argument("--exchange-width", type=int, default=4)
    parser.add_argument(
        "--engine", choices=["numpy", "jax", "swar"], default="numpy",
        help="worker tile engine (numpy = portable default; the wire "
        "plane under test is engine-independent)",
    )
    parser.add_argument(
        "--grow-at", type=int, default=None, metavar="E",
        help="elastic drill: grow the cluster to --grow-to workers once "
        "the epoch floor crosses E (reports cell-updates/s before/after)",
    )
    parser.add_argument(
        "--grow-to", type=int, default=4, metavar="N",
        help="worker count after the --grow-at scale-out (default 4)",
    )
    parser.add_argument(
        "--drain-at", type=int, default=None, metavar="E",
        help="elastic drill: gracefully drain one loaded worker once the "
        "epoch floor crosses E (asserts zero redeploys, digest-certified)",
    )
    parser.add_argument(
        "--drill-chaos", action="store_true",
        help="arm the elastic drill with peer-plane netchaos (5%% drops + "
        "one scheduled partition)",
    )
    parser.add_argument(
        "--sweep-exchange-width", default=None, metavar="T1,T2,...",
        help="temporal-blocking T-sweep: run the same seeded cluster at "
        "each exchange width (e.g. 1,2,4,8), digest-certified against the "
        "dense oracle, reporting throughput per T",
    )
    parser.add_argument(
        "--sparse", action="store_true",
        help="dilute-universe drill: the same glider board with "
        "sparse_cluster off vs on (quiescent tiles skip their chunks), "
        "digest-certified, reporting the epochs/s speedup",
    )
    parser.add_argument(
        "--pattern", default="glider",
        help="seed pattern for the --sparse dilute board (default glider)",
    )
    parser.add_argument(
        "--platform", default=None, help="pin jax platform (e.g. cpu)"
    )
    args = parser.parse_args()

    from akka_game_of_life_tpu.cli import _apply_platform

    _apply_platform(args.platform)
    if args.sweep_exchange_width is not None:
        try:
            widths = tuple(
                int(v) for v in args.sweep_exchange_width.split(",")
            )
        except ValueError:
            raise SystemExit(
                f"bad --sweep-exchange-width "
                f"{args.sweep_exchange_width!r}; expected e.g. 1,2,4,8"
            )
        bench_cluster_tsweep(
            size=args.size,
            epochs=args.epochs if args.epochs is not None else 64,
            workers=args.workers,
            widths=widths,
            tiles_per_worker=(
                args.tiles_per_worker if args.tiles_per_worker is not None else 4
            ),
            engine=args.engine,
        )
        return 0
    if args.sparse:
        bench_cluster_sparse(
            size=args.size,
            epochs=args.epochs if args.epochs is not None else 64,
            workers=args.workers,
            tiles_per_worker=(
                args.tiles_per_worker if args.tiles_per_worker is not None else 4
            ),
            exchange_width=args.exchange_width,
            engine=args.engine,
            pattern=args.pattern,
        )
        return 0
    if args.grow_at is not None or args.drain_at is not None:
        bench_cluster_elastic(
            size=args.size,
            epochs=args.epochs if args.epochs is not None else 96,
            workers=args.workers,
            grow_to=args.grow_to,
            grow_at=args.grow_at,
            drain_at=args.drain_at,
            tiles_per_worker=(
                args.tiles_per_worker if args.tiles_per_worker is not None else 4
            ),
            exchange_width=args.exchange_width,
            engine=args.engine,
            chaos=args.drill_chaos,
        )
        return 0
    bench_cluster_halo(
        size=args.size,
        epochs=args.epochs if args.epochs is not None else 32,
        workers=args.workers,
        tiles_per_worker=(
            args.tiles_per_worker if args.tiles_per_worker is not None else 8
        ),
        exchange_width=args.exchange_width,
        engine=args.engine,
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
