"""Distributed span tracing — the causal timeline the metrics registry can't
give.

PR 1's counters say *how many* peer retries fired; this tracer says *which
frontend epoch caused them, on which worker, between which halo sends*.  A
span is (trace_id, span_id, parent_id) plus per-node / per-epoch / per-tile
attributes and a monotonic duration; span context rides the cluster wire
protocol inside message envelopes (:data:`TRACE_KEY`, attached by
``runtime/wire.attach_trace``), so one frontend ``epoch`` span links to every
``backend.step``, ``halo.send``/``halo.recv``/``halo.retry``,
``checkpoint.save`` and ``recover.redeploy`` span it transitively caused —
across threads in the in-process harness and across processes in a real
cluster (same ids, one file per process, mergeable by trace_id).

Export is Chrome trace-event JSON (the Perfetto / ``chrome://tracing``
format): ``--trace-file PATH`` writes it on close, and the obs HTTP endpoint
serves the live buffer at ``/trace``.  Timestamps anchor on the wall clock
(cross-node alignment) while durations come from the monotonic clock
(immune to wall jumps) — the same dual-clock contract as the event log.

Nesting is implicit within a thread (a module-level stack, so
``profiling.timed()`` blocks become children of whatever span is active
without knowing about the tracer) and explicit across threads/processes
(pass ``parent=`` a span, its ``ctx``, or a wire dict).

Every span name the runtime emits is declared in :data:`SPAN_CATALOG`;
``tools/check_trace_names.py`` (tier-1) lints that each appears in
``docs/OPERATIONS.md`` so the operator-facing table cannot rot.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Union

# The wire-envelope key span context rides under (see runtime/wire.py
# attach_trace/extract_trace).  Underscored so it can never collide with a
# protocol payload field.
TRACE_KEY = "_trace"

# Every span name the runtime emits, with its meaning — the single source of
# truth the OPERATIONS.md "Tracing" table and tools/check_trace_names.py
# lint against (the exact analog of obs/catalog.py for metrics).  Spans
# minted by profiling.timed() reuse its @-stripped label (e.g.
# ``checkpoint``) and are documented with the table, not listed here.
SPAN_CATALOG = (
    # -- standalone runtime ---------------------------------------------------
    ("sim.advance", "one Simulation.advance() call (the standalone run loop)"),
    ("sim.chunk", "one stepper chunk (steps_per_call epochs, one device round-trip)"),
    ("sim.fastforward", "one O(log T) linear-rule jump (certify + jump + "
     "board swap)"),
    ("chaos.crash", "injected crash taking effect (state discarded)"),
    ("chaos.recover", "checkpoint restore + deterministic replay after a crash"),
    # -- cluster frontend -----------------------------------------------------
    ("cluster.run", "the whole cluster simulation, start_simulation to done"),
    ("epoch", "one epoch-target announcement driving every tile toward it"),
    ("cluster.deploy", "one DEPLOY batch shipped to a worker"),
    ("recover.redeploy", "tile redeployed from the recovery source"),
    ("member.lost", "node loss handled (eviction + orphaned-tile recovery)"),
    ("migrate.tile", "one live tile migration, PREPARE to COMMIT or abort"),
    ("cluster.drain", "one graceful worker drain, request to release"),
    # -- cluster backend ------------------------------------------------------
    ("backend.step", "one tile chunk stepped on a worker"),
    ("halo.send", "boundary ring encoded and queued for remote peer owners"),
    ("halo.batch_send", "one coalesced PEER_RING_BATCH frame written to a peer"),
    ("halo.recv", "PEER_RING received and stored"),
    ("halo.serve", "PEER_PULL answered from the local ring store"),
    ("halo.retry", "stale-halo retry round (re-asks to missing rings' owners)"),
    ("gather.escalate", "GATHER_FAILED escalation after the retry budget"),
    ("backend.crash", "CRASH/CRASH_TILE handled on the worker"),
    ("tile.quiesce", "a tile entering quiescence (sparse_cluster: chunks "
     "skipped until a neighboring ring changes)"),
    # -- network chaos plane / hardened comms ---------------------------------
    ("net.partition", "one injected partition, open to heal"),
    ("breaker.open", "one circuit-breaker open interval, open to re-close"),
    ("cluster.degraded", "frontend degraded mode, quorum-stranded to heal"),
    # -- multi-tenant serving plane -------------------------------------------
    ("serve.tick", "one serving-plane engine tick (batched device programs "
     "over this tick's step jobs)"),
    ("serve.memo", "one tick's memoized macro-step phase: lockstep "
     "macro-rounds over the tick's eligible jobs, one batched device "
     "call of deduplicated cache misses per round (child of serve.tick)"),
    ("serve.shard_migrate", "one session-shard migration, PREPARE to "
     "COMMIT or abort (cluster-sharded serving)"),
    ("serve.promote", "one shard replica promoted to primary after a "
     "worker loss (digest-certified; sessions resume at their "
     "replicated epoch)"),
    ("serve.fed_promote", "one federation promotion window: a dead "
     "frontend's slice adopted from replicated control rows, open until "
     "the orphaned worker's shard_home announcement (or expiry = honest "
     "session loss)"),
    ("serve.request", "one HTTP request against the /boards surface, "
     "minted (or adopted) at the edge — the root every serve-plane span "
     "for that request links under"),
    ("serve.batch", "one step job executed on a serving worker, op "
     "arrival to result push (queue wait + its slice of the vmapped "
     "batch), child of the serve.request that caused it"),
    ("serve.canary", "one synthetic canary probe round: step the pinned "
     "known-orbit session over real HTTP and digest-certify the answer "
     "against the precomputed oracle trajectory"),
    # -- durability -----------------------------------------------------------
    ("checkpoint.save", "one checkpoint save made durable"),
    ("checkpoint.restore", "one checkpoint load"),
    # -- digest certification plane -------------------------------------------
    ("obs.digest", "one board digest: computed+fetched on device "
     "(standalone) or merged from per-tile lanes (frontend)"),
)

_SPAN_NAMES = frozenset(n for n, _ in SPAN_CATALOG)


class Span:
    """One timed operation.  Created by :meth:`Tracer.span` /
    :meth:`Tracer.start`; immutable identity, mutable attrs until
    :meth:`finish`."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "node",
        "t0_wall", "t0_mono", "duration", "attrs", "tid", "_tracer", "_done",
    )

    def __init__(
        self, tracer: "Tracer", name: str, trace_id: str, span_id: str,
        parent_id: Optional[str], node: str, t0_wall: float, t0_mono: float,
        tid: int, attrs: Dict[str, object],
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.node = node
        self.t0_wall = t0_wall
        self.t0_mono = t0_mono
        self.tid = tid
        self.attrs = attrs
        self.duration: Optional[float] = None
        self._done = False

    @property
    def ctx(self) -> Dict[str, str]:
        """The wire-safe propagation context: what a message envelope
        carries so the receiver's spans join this trace."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def finish(self) -> None:
        """Record the span (idempotent — a double finish keeps the first
        duration)."""
        if self._done:
            return
        self._done = True
        self.duration = self._tracer._clock() - self.t0_mono
        self._tracer._record_finished(self)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "node": self.node,
            "t0_wall": self.t0_wall,
            "t0_mono": self.t0_mono,
            "duration": self.duration,
            "tid": self.tid,
            "attrs": dict(self.attrs),
        }

    # Context-manager form: pushes onto the thread's span stack so nested
    # spans (and profiling.timed blocks) parent themselves automatically.
    def __enter__(self) -> "Span":
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> bool:
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        self.finish()
        return False


# Module-level (not per-tracer) active-span stack: profiling.timed() and any
# other instrumentation can ask "what span is active on this thread" without
# holding a tracer reference — and in the in-process cluster harness, spans
# from one shared tracer nest naturally across component boundaries.
_local = threading.local()


def _stack() -> List[Span]:
    stack = getattr(_local, "spans", None)
    if stack is None:
        stack = _local.spans = []
    return stack


def current() -> Optional[Span]:
    """The innermost span active on THIS thread (None outside any span)."""
    stack = getattr(_local, "spans", None)
    return stack[-1] if stack else None


def record_timed(label: str, seconds: float, span: Optional[str] = None) -> None:
    """Attach an after-the-fact measurement as a child of the active span.

    The bridge profiling.timed() calls on exit: when a trace is active on
    this thread, the timed block becomes a proper child span (named from
    ``span`` or the label up to the first ``@`` — epoch-stamped labels must
    not mint one span name per epoch, same rule as the metrics histogram);
    with no active span it is a no-op, so spanless code paths cost one
    attribute check.
    """
    parent = current()
    if parent is None:
        return
    tracer = parent._tracer
    name = span or label.split("@", 1)[0]
    now_mono = tracer._clock()
    child = tracer.start(
        name, parent=parent, node=parent.node, label=label
    )
    # Back-date the start to when the measured block began.
    child.t0_mono = now_mono - seconds
    child.t0_wall = tracer._wall() - seconds
    child.duration = seconds
    child._done = True
    tracer._record_finished(child)


_Parent = Union[Span, Dict[str, str], None]


class Tracer:
    """Thread-safe span factory + bounded buffer + Perfetto exporter.

    One per process by default (:func:`get_tracer`); tests inject isolated
    instances with deterministic clocks/ids.  Finished spans land in a
    bounded ring (oldest dropped, counted in :attr:`dropped`) and are teed
    into the attached :class:`~akka_game_of_life_tpu.obs.flight.FlightRecorder`
    so the crash dump always holds the most recent causal history.
    """

    def __init__(
        self,
        node: str = "proc",
        *,
        max_spans: int = 65536,
        recorder=None,
        seed: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
        ident: Callable[[], int] = threading.get_ident,
    ) -> None:
        self.node = node
        self._clock = clock
        self._wall = wallclock
        self._ident = ident
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._finished: deque = deque(maxlen=max_spans)
        self.dropped = 0
        self._epoch_wall = wallclock()
        self._sinks: List[Callable[[dict], None]] = []
        if recorder is None:
            from akka_game_of_life_tpu.obs.flight import FlightRecorder

            recorder = FlightRecorder(node=node)
        self.flight = recorder

    # -- span creation -------------------------------------------------------

    def _ids(self, parent: _Parent) -> tuple:
        if isinstance(parent, Span):
            return parent.trace_id, parent.span_id
        if isinstance(parent, dict) and parent.get("trace_id"):
            return str(parent["trace_id"]), parent.get("span_id")
        with self._lock:
            return f"{self._rng.getrandbits(128):032x}", None

    def _span_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(64):016x}"

    def start(
        self, name: str, *, parent: _Parent = None, node: Optional[str] = None,
        **attrs,
    ) -> Span:
        """Create a live span.  ``parent`` is a Span, a wire ctx dict, or
        None — None adopts this thread's active span, or roots a new trace.
        The caller owns calling :meth:`Span.finish` (or use the span as a
        context manager for stack-nesting semantics)."""
        if parent is None:
            parent = current()
        trace_id, parent_id = self._ids(parent)
        return Span(
            self, name, trace_id, self._span_id(), parent_id,
            node or self.node, self._wall(), self._clock(), self._ident(),
            dict(attrs),
        )

    def span(
        self, name: str, *, parent: _Parent = None, node: Optional[str] = None,
        **attrs,
    ) -> Span:
        """:meth:`start`, intended for ``with`` use (enter pushes the span
        onto the thread stack; exit pops and finishes it)."""
        return self.start(name, parent=parent, node=node, **attrs)

    def _record_finished(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            if len(self._finished) == self._finished.maxlen:
                self.dropped += 1
            self._finished.append(d)
        if self.flight is not None:
            # Pass the dict, not the span: record_span would re-serialize.
            self.flight.record_span(d)
        for sink in self._sinks:
            sink(d)

    def add_sink(self, fn: Callable[[dict], None]) -> None:
        """Subscribe to finished-span dicts (the cluster worker's
        span-forwarding hook).  Sinks run on the finishing thread and must
        be fast and non-raising."""
        self._sinks.append(fn)

    def ingest(self, spans) -> None:
        """Append span dicts produced by ANOTHER tracer (a worker process
        forwarding over the control plane) into this buffer, so the
        frontend's export is the cluster-wide document.  Ids come through
        verbatim — causality links survive the hop.  Entries missing the
        span shape are dropped here (the frontend port is an open TCP
        listener; a malformed batch must not be able to poison every
        later export)."""
        with self._lock:
            for s in spans:
                if not (
                    isinstance(s, dict)
                    and isinstance(s.get("span_id"), str)
                    and isinstance(s.get("name"), str)
                ):
                    continue
                if len(self._finished) == self._finished.maxlen:
                    self.dropped += 1
                self._finished.append(s)

    # -- introspection / export ----------------------------------------------

    def finished(self) -> List[dict]:
        """Finished spans, oldest first (the assertion surface for tests)."""
        with self._lock:
            return list(self._finished)

    def export(self) -> dict:
        """The buffer as a Chrome trace-event / Perfetto JSON object.

        Spans become ``ph: "X"`` complete events; each distinct node label
        becomes a pid with a ``process_name`` metadata event, so a cluster's
        workers render as separate process tracks.  ``ts`` anchors on the
        wall clock relative to tracer creation (microseconds — cross-node
        alignment after a merge); ``dur`` is the monotonic duration.  The
        (trace_id, span_id, parent_id) triple rides in ``args`` for tools
        that rebuild causality exactly.
        """
        spans = self.finished()
        pids: Dict[str, int] = {}
        events: List[dict] = []
        # .get() throughout: ingested spans crossed an unauthenticated wire
        # (see ingest) and one short field must not break every export.
        for s in spans:
            node = str(s.get("node", "?"))
            if node not in pids:
                pid = pids[node] = len(pids)
                events.append(
                    {
                        "ph": "M",
                        "name": "process_name",
                        "pid": pid,
                        "args": {"name": node},
                    }
                )
        for s in spans:
            args = {
                "trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
            }
            attrs = s.get("attrs")
            if isinstance(attrs, dict):
                args.update(attrs)
            try:
                ts = (float(s.get("t0_wall", 0.0)) - self._epoch_wall) * 1e6
                dur = float(s.get("duration") or 0.0) * 1e6
            except (TypeError, ValueError):
                ts, dur = 0.0, 0.0
            events.append(
                {
                    "ph": "X",
                    "name": s["name"],
                    "cat": "gol",
                    "pid": pids[str(s.get("node", "?"))],
                    "tid": s.get("tid", 0),
                    "ts": round(ts, 3),
                    "dur": round(dur, 3),
                    "args": args,
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_json(self) -> str:
        return json.dumps(self.export(), separators=(",", ":"))

    def write(self, path: str) -> None:
        """Dump the Perfetto JSON atomically (tmp + rename), creating parent
        directories — the same durability idiom as the metrics exposition."""
        from akka_game_of_life_tpu.obs.ioutil import atomic_write_text

        atomic_write_text(path, self.export_json(), prefix=".trace_")


def to_dict(span: Span) -> dict:
    """Span → plain dict, exported for flight/tooling callers."""
    return span.to_dict()


_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """The process-wide default tracer (created on first use, with a flight
    recorder attached so the last-N-spans ring is always armed)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Tracer()
        return _GLOBAL
