"""Shared file-IO idiom for observability artifacts.

One implementation of the atomic text dump (tmp + rename, parent dirs
created, tmp unlinked on failure) that the metrics exposition, the Perfetto
trace export, and the flight-recorder dump all use — a scrape or post-
mortem read never sees a torn write, and a durability fix (e.g. adding
fsync) lands in one place.
"""

from __future__ import annotations

import os
import tempfile


def atomic_write_text(path: str, text: str, *, prefix: str = ".tmp_") -> None:
    """Write ``text`` to ``path`` atomically (same-directory tmp + rename),
    creating parent directories.  Raises OSError on failure with the tmp
    file cleaned up."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=prefix)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
