"""Unified observability: metrics registry, structured events, exposition.

The runtime's answer to "what is the steps/s right now, how many peer
retries fired, how many chaos crashes were recovered" — without grepping
stdout:

- :class:`MetricsRegistry` — thread-safe counters/gauges/histograms,
  rendered as Prometheus text exposition (``registry.render()`` /
  ``registry.write(path)``);
- :class:`EventLog` — structured JSONL lifecycle events with monotonic
  timestamps and per-node labels (``--log-events``);
- :class:`MetricsServer` — live ``/metrics`` + ``/healthz`` HTTP endpoint
  (``--metrics-port``);
- :mod:`.catalog` — every exported metric, declared once, pre-registered
  into the default registry and lint-checked against the operations doc.

Instrumented layers: the simulation hot loop, the cluster backend's peer
data plane and retry machinery, the frontend's membership/redeploy paths,
the chaos injector, and both checkpoint stores.
"""

from akka_game_of_life_tpu.obs.catalog import CATALOG, install
from akka_game_of_life_tpu.obs.events import NULL_EVENTS, EventLog, read_events
from akka_game_of_life_tpu.obs.httpd import MetricsServer
from akka_game_of_life_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    escape_label_value,
    get_registry,
)

__all__ = [
    "CATALOG",
    "DEFAULT_BUCKETS",
    "EventLog",
    "MetricsRegistry",
    "MetricsServer",
    "NULL_EVENTS",
    "escape_label_value",
    "get_registry",
    "install",
    "read_events",
]
