"""Unified observability: metrics, events, tracing, flight recorder.

The runtime's answer to "what is the steps/s right now, how many peer
retries fired, which epoch caused them, and what happened in the second
before that worker died" — without grepping stdout:

- :class:`MetricsRegistry` — thread-safe counters/gauges/histograms,
  rendered as Prometheus text exposition (``registry.render()`` /
  ``registry.write(path)``);
- :class:`EventLog` — structured JSONL lifecycle events with monotonic
  timestamps and per-node labels (``--log-events``);
- :class:`Tracer` — causally-linked spans (trace/span/parent ids) whose
  context propagates through the cluster wire protocol; exported as
  Chrome trace-event / Perfetto JSON (``--trace-file``, ``/trace``);
- :class:`FlightRecorder` — a bounded ring of the last N spans + events,
  dumped to ``artifacts/flightrec-<node>-<ts>.json`` on crashes,
  supervision replays, node-loss redeploys, and SIGTERM;
- :class:`MetricsServer` — live ``/metrics`` + ``/healthz`` + ``/trace``
  HTTP endpoint (``--metrics-port``);
- :class:`MetricsDumper` — the shared ``--metrics-file`` dump policy
  (atomic writes, warn-once failure containment) every role uses;
- :class:`ProgramRegistry` (:mod:`.programs`) — the jit-program ledger:
  compile bills, per-family throughput/roofline pricing, compile-storm
  alerts, and the cluster-merged ``/programs`` + ``/cost`` endpoints;
- :mod:`.catalog` — every exported metric, declared once, pre-registered
  into the default registry and lint-checked against the operations doc
  (span names get the same treatment via ``tracing.SPAN_CATALOG`` and
  ``tools/check_trace_names.py``).

Instrumented layers: the simulation hot loop, the cluster backend's peer
data plane and retry machinery, the frontend's membership/redeploy paths,
the chaos injector, and both checkpoint stores.
"""

from akka_game_of_life_tpu.obs.catalog import CATALOG, install
from akka_game_of_life_tpu.obs.dump import MetricsDumper
from akka_game_of_life_tpu.obs.events import NULL_EVENTS, EventLog, read_events
from akka_game_of_life_tpu.obs.flight import FlightRecorder, read_flight
from akka_game_of_life_tpu.obs.httpd import MetricsServer
from akka_game_of_life_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    escape_label_value,
    get_registry,
)
from akka_game_of_life_tpu.obs.programs import (
    ProgramRegistry,
    get_programs,
    registered_jit,
)
from akka_game_of_life_tpu.obs.tracing import (
    SPAN_CATALOG,
    TRACE_KEY,
    Span,
    Tracer,
    get_tracer,
)

__all__ = [
    "CATALOG",
    "DEFAULT_BUCKETS",
    "EventLog",
    "FlightRecorder",
    "MetricsDumper",
    "MetricsRegistry",
    "MetricsServer",
    "NULL_EVENTS",
    "ProgramRegistry",
    "SPAN_CATALOG",
    "Span",
    "TRACE_KEY",
    "Tracer",
    "escape_label_value",
    "get_programs",
    "get_registry",
    "get_tracer",
    "install",
    "registered_jit",
    "read_events",
    "read_flight",
]
