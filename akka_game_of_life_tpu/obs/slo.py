"""Per-tenant SLO accounting for the serving plane.

The metrics registry says *how fast* the serve path is; this tracker says
*whether we are keeping the promise*: every ``/boards`` request lands here
with its tenant, route, outcome, queue wait, latency, and trace id, and
three products fall out:

- a **structured JSONL access log** (``serve_slo_log``) — one line per
  request, the replayable ground truth ``tools/slo_report.py`` folds into
  a per-tenant SLO table;
- **per-tenant RED metrics** (``gol_serve_slo_*``) with the PR 7
  label-reclaim hygiene: tenant cardinality is capped at
  ``serve_slo_max_tenants``, the least-recently-seen tenant's series are
  removed from the exposition and its traffic folds into
  ``tenant="~overflow"`` — a tenant id is client-supplied and must never
  be an unbounded-cardinality lever.  The latency histogram records
  **trace-id exemplars**, so a p99 bucket clicks through to a concrete
  trace in the ``/trace`` export;
- a **sliding multi-window burn-rate tracker**: two objectives
  (availability — 5xx/timeouts over everything; latency — slow OKs over
  OKs, both scored against ``serve_slo_availability``'s target fraction)
  over per-second ring buckets spanning ``serve_slo_slow_window_s``.  An
  alert fires only when BOTH the fast and the slow window burn error
  budget faster than :data:`BURN_THRESHOLD` — the standard multiwindow
  discipline (the fast window catches the cliff, the slow window keeps a
  blip from paging), and it is transition-edged: one ``slo_burn_alert``
  event + one flight dump (``reason=slo_burn``) per False→True edge,
  one all-clear event per True→False, never a per-request stream.

A 429 is a **correct answer**, not a burn: admission control shedding
load is the plane working as designed, so rejects count toward traffic
but toward neither objective.

``/slo`` on the obs endpoint serves :meth:`SloTracker.summary` live.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from akka_game_of_life_tpu.obs.events import NULL_EVENTS

# Budget-burn multiple both windows must exceed before the alert edges:
# at 14.4x a 99.9% objective's whole 30-day budget dies in ~2 days — the
# classic "page now" rate (2% of a 30-day budget per hour).
BURN_THRESHOLD = 14.4

# Ring ceiling: one bucket per second, so a day-long slow window is the
# largest we will hold resident (config validation keeps windows sane;
# this is the allocation backstop).
_MAX_BUCKETS = 86_400

# The label every evicted tenant's traffic folds into.  "~" keeps it
# outside the client-legal tenant alphabet, so a real tenant can never
# collide with (or squat on) the overflow series.
OVERFLOW_TENANT = "~overflow"


# -- queue-wait relay ---------------------------------------------------------
# The queue wait is measured deep in the engine (the ticker stamping a job,
# a worker echoing it on a serve_result) while the access-log line is cut
# at the HTTP edge on the request thread.  A thread-local hands the number
# up the stack without threading a context object through every layer.
_tl = threading.local()


def note_queue_wait(seconds: Optional[float]) -> None:
    """Record this request thread's queue wait (engine-side callers)."""
    _tl.queue_wait_s = seconds


def take_queue_wait() -> Optional[float]:
    """Consume the queue wait noted on this thread (edge-side caller);
    clears it so one request's wait can never bleed into the next."""
    qw = getattr(_tl, "queue_wait_s", None)
    _tl.queue_wait_s = None
    return qw


class _Window:
    """Per-second ring of (total, avail_bad, ok, lat_bad) buckets — O(1)
    record, O(window) read, bounded memory regardless of uptime."""

    def __init__(self, span_s: int) -> None:
        self.span = max(1, min(int(span_s), _MAX_BUCKETS))
        # [second_epoch, total, avail_bad, ok, lat_bad] per slot; the
        # epoch tag lazily zeroes slots last written a full lap ago.
        self.slots = [[-1, 0, 0, 0, 0] for _ in range(self.span)]

    def add(self, sec: int, avail_bad: bool, ok: bool, lat_bad: bool) -> None:
        slot = self.slots[sec % self.span]
        if slot[0] != sec:
            slot[0], slot[1], slot[2], slot[3], slot[4] = sec, 0, 0, 0, 0
        slot[1] += 1
        slot[2] += 1 if avail_bad else 0
        slot[3] += 1 if ok else 0
        slot[4] += 1 if lat_bad else 0

    def sums(self, now_sec: int, window_s: int) -> tuple:
        """(total, avail_bad, ok, lat_bad) over the trailing window."""
        lo = now_sec - min(int(window_s), self.span) + 1
        total = avail_bad = ok = lat_bad = 0
        for slot in self.slots:
            if lo <= slot[0] <= now_sec:
                total += slot[1]
                avail_bad += slot[2]
                ok += slot[3]
                lat_bad += slot[4]
        return total, avail_bad, ok, lat_bad


class SloTracker:
    """Access log + per-tenant RED metrics + multi-window burn alerting.

    Thread-safe; one per serve surface (the single-process router and the
    cluster frontend each mount one on their obs endpoint).  ``clock`` is
    injectable so the burn-window drills are deterministic."""

    def __init__(
        self,
        config=None,
        *,
        registry=None,
        tracer=None,
        events=None,
        node: str = "serve",
        clock=time.monotonic,
        wallclock=time.time,
    ) -> None:
        get = (lambda k, d: getattr(config, k, d)) if config else (
            lambda k, d: d
        )
        self.availability = float(get("serve_slo_availability", 0.999))
        self.latency_s = float(get("serve_slo_latency_ms", 250.0)) / 1e3
        self.fast_window_s = float(get("serve_slo_fast_window_s", 300.0))
        self.slow_window_s = float(get("serve_slo_slow_window_s", 3600.0))
        self.max_tenants = int(get("serve_slo_max_tenants", 64))
        self.log_path = str(get("serve_slo_log", "") or "")
        self.node = node
        self._clock = clock
        self._wall = wallclock
        self.events = events if events is not None else NULL_EVENTS
        if registry is None:
            from akka_game_of_life_tpu.obs.metrics import get_registry

            registry = get_registry()
        self.metrics = registry
        self.tracer = tracer
        self._m_requests = registry.counter(
            "gol_serve_slo_requests_total",
            labelnames=("tenant", "route", "outcome"),
        )
        self._m_latency = registry.histogram(
            "gol_serve_slo_latency_seconds", labelnames=("tenant",)
        )
        self._m_queue_wait = registry.histogram(
            "gol_serve_slo_queue_wait_seconds"
        )
        self._m_burn = registry.gauge(
            "gol_serve_slo_burn_rate", labelnames=("objective", "window")
        )
        self._m_alert = registry.gauge(
            "gol_serve_slo_burn_alert", labelnames=("objective",)
        )
        self._m_alerts = registry.counter(
            "gol_serve_slo_alerts_total", labelnames=("objective",)
        )
        self._m_tenants = registry.gauge("gol_serve_slo_tenants")
        self._lock = threading.Lock()
        self._window = _Window(int(self.slow_window_s))  # graftlint: guarded-by _lock
        # tenant -> {"series": set of (route, outcome), "stats": dict},
        # LRU-ordered so the cardinality cap evicts the coldest tenant.
        self._tenants: "OrderedDict[str, dict]" = OrderedDict()  # graftlint: guarded-by _lock
        self._alerting = {"availability": False, "latency": False}  # graftlint: guarded-by _lock
        self._last_check = -1  # graftlint: guarded-by _lock
        self._log_fh = None
        self._log_lock = threading.Lock()
        if self.log_path:
            import os

            d = os.path.dirname(self.log_path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._log_fh = open(  # noqa: SIM115 — held for the tracker's life
                self.log_path, "a", encoding="utf-8", buffering=1
            )

    # -- recording -----------------------------------------------------------

    @staticmethod
    def outcome_of(status: int) -> str:
        if status < 300:
            return "ok"
        if status == 429:
            return "rejected"
        if status < 500:
            return "client_error"
        return "error"

    def record(
        self,
        *,
        route: str,
        tenant: str = "default",
        sid: Optional[str] = None,
        status: int = 200,
        reason: Optional[str] = None,
        latency_s: float = 0.0,
        queue_wait_s: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Score one finished request into every SLO product."""
        outcome = self.outcome_of(int(status))
        ok = outcome == "ok"
        avail_bad = outcome == "error"
        lat_bad = ok and latency_s > self.latency_s
        with self._lock:
            label_tenant = self._touch_tenant_locked(
                tenant, route, outcome, ok, avail_bad, lat_bad, latency_s
            )
            sec = int(self._clock())
            self._window.add(sec, avail_bad, ok, lat_bad)
            edges = self._check_burn_locked(sec)
        self._m_requests.labels(
            tenant=label_tenant, route=route, outcome=outcome
        ).inc()
        exemplar = {"trace_id": trace_id} if trace_id else None
        self._m_latency.labels(tenant=label_tenant).observe(
            latency_s, exemplar
        )
        if queue_wait_s is not None:
            self._m_queue_wait.observe(float(queue_wait_s))
        if self._log_fh is not None:
            line = json.dumps(
                {
                    "t": round(self._wall(), 6),
                    "trace": trace_id,
                    "tenant": tenant,
                    "route": route,
                    "sid": sid,
                    "status": int(status),
                    "outcome": outcome,
                    "reason": reason,
                    "queue_wait_s": (
                        round(queue_wait_s, 6)
                        if queue_wait_s is not None
                        else None
                    ),
                    "latency_s": round(latency_s, 6),
                },
                separators=(",", ":"),
            )
            with self._log_lock:
                self._log_fh.write(line + "\n")
        for objective, alerting, burns in edges:
            self._edge_alert(objective, alerting, burns, trace_id)

    def _touch_tenant_locked(
        self, tenant, route, outcome, ok, avail_bad, lat_bad, latency_s
    ) -> str:
        """LRU-touch the tenant; evict + reclaim past the cap.  Returns
        the label to record under (the tenant, or the overflow fold)."""
        entry = self._tenants.get(tenant)
        if entry is None:
            if (
                len(self._tenants) >= self.max_tenants
                and tenant != OVERFLOW_TENANT
            ):
                # Reclaim the coldest tenant's exposition series (PR 7
                # hygiene), fold the newcomer into the overflow label.
                old_tenant, old = self._tenants.popitem(last=False)
                for r, o in old["series"]:
                    self._m_requests.remove(
                        tenant=old_tenant, route=r, outcome=o
                    )
                self._m_latency.remove(tenant=old_tenant)
                self._m_tenants.set(len(self._tenants))
                return self._touch_tenant_locked(
                    OVERFLOW_TENANT, route, outcome, ok, avail_bad,
                    lat_bad, latency_s,
                )
            entry = self._tenants[tenant] = {
                "series": set(),
                "stats": {
                    "requests": 0, "ok": 0, "errors": 0, "rejected": 0,
                    "latency_bad": 0, "latency_sum": 0.0,
                },
            }
            self._m_tenants.set(len(self._tenants))
        else:
            self._tenants.move_to_end(tenant)
        entry["series"].add((route, outcome))
        st = entry["stats"]
        st["requests"] += 1
        st["ok"] += 1 if ok else 0
        st["errors"] += 1 if avail_bad else 0
        st["rejected"] += 1 if outcome == "rejected" else 0
        st["latency_bad"] += 1 if lat_bad else 0
        st["latency_sum"] += latency_s
        return tenant

    # -- burn-rate alerting --------------------------------------------------

    def _burns_locked(self, sec: int) -> Dict[str, Dict[str, float]]:
        """{objective: {window: burn_rate}} over the trailing windows.
        Burn 1.0 = consuming exactly the error budget; > BURN_THRESHOLD in
        both windows pages."""
        budget = max(1e-9, 1.0 - self.availability)
        out: Dict[str, Dict[str, float]] = {
            "availability": {}, "latency": {},
        }
        for wname, wspan in (
            ("fast", self.fast_window_s), ("slow", self.slow_window_s),
        ):
            total, avail_bad, ok, lat_bad = self._window.sums(
                sec, int(wspan)
            )
            out["availability"][wname] = (
                (avail_bad / total) / budget if total else 0.0
            )
            out["latency"][wname] = (
                (lat_bad / ok) / budget if ok else 0.0
            )
        return out

    def _check_burn_locked(self, sec: int) -> list:
        """At most one evaluation per second; returns the transition
        edges to emit (outside the lock)."""
        if sec == self._last_check:
            return []
        self._last_check = sec
        burns = self._burns_locked(sec)
        edges = []
        for objective, by_window in burns.items():
            for wname, rate in by_window.items():
                self._m_burn.labels(objective=objective, window=wname).set(
                    round(rate, 4)
                )
            burning = all(
                rate > BURN_THRESHOLD for rate in by_window.values()
            )
            if burning != self._alerting[objective]:
                self._alerting[objective] = burning
                edges.append((objective, burning, dict(by_window)))
        return edges

    def _edge_alert(self, objective, alerting, burns, trace_id) -> None:
        self._m_alert.labels(objective=objective).set(1 if alerting else 0)
        self.events.emit(
            "slo_burn_alert",
            objective=objective,
            state="firing" if alerting else "resolved",
            burn_fast=round(burns.get("fast", 0.0), 3),
            burn_slow=round(burns.get("slow", 0.0), 3),
            threshold=BURN_THRESHOLD,
            trace=trace_id,
        )
        if alerting:
            self._m_alerts.labels(objective=objective).inc()
            if self.tracer is not None and self.tracer.flight is not None:
                self.tracer.flight.dump("slo_burn", node=self.node)

    # -- exposition ----------------------------------------------------------

    def summary(self) -> dict:
        """The ``/slo`` document: objectives, live burn rates + alert
        states, per-tenant availability/latency, and the latency
        exemplars that link buckets to traces."""
        with self._lock:
            sec = int(self._clock())
            burns = self._burns_locked(sec)
            alerting = dict(self._alerting)
            tenants = {
                t: dict(e["stats"]) for t, e in self._tenants.items()
            }
        per_tenant = {}
        for t, st in tenants.items():
            n = st["requests"]
            scored = max(1, n - st["rejected"])
            per_tenant[t] = {
                "requests": n,
                "rejected": st["rejected"],
                "availability": round(1.0 - st["errors"] / scored, 6),
                "latency_ok_ratio": round(
                    1.0 - st["latency_bad"] / max(1, st["ok"]), 6
                ),
                "mean_latency_s": round(st["latency_sum"] / max(1, n), 6),
            }
            child = self._m_latency.labels(tenant=t)
            snap = child.snapshot()
            per_tenant[t]["exemplars"] = child.exemplar_snapshot()
            per_tenant[t]["latency_count"] = snap["count"]
        return {
            "objectives": {
                "availability": self.availability,
                "latency_ms": round(self.latency_s * 1e3, 3),
                "burn_threshold": BURN_THRESHOLD,
            },
            "windows": {
                "fast_s": self.fast_window_s,
                "slow_s": self.slow_window_s,
            },
            "burn": burns,
            "alerting": alerting,
            "tenants": per_tenant,
            "access_log": self.log_path or None,
        }

    def close(self) -> None:
        if self._log_fh is not None:
            with self._log_lock:
                try:
                    self._log_fh.close()
                finally:
                    self._log_fh = None


def read_access_log(path: str) -> list:
    """Parse a JSONL access log back into dicts (tests/tooling twin of
    the writer; torn trailing lines are skipped, matching read_events)."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def fold_report(records) -> dict:
    """Fold access-log records into a per-tenant SLO table — the
    ``tools/slo_report.py`` engine, importable for the tier-1 smoke
    test.  Pure function: records in, table out."""
    tenants: Dict[str, dict] = {}
    for r in records:
        t = str(r.get("tenant", "default"))
        st = tenants.setdefault(
            t,
            {
                "requests": 0, "ok": 0, "errors": 0, "rejected": 0,
                "latencies": [],
            },
        )
        st["requests"] += 1
        outcome = r.get("outcome")
        if outcome == "ok":
            st["ok"] += 1
        elif outcome == "error":
            st["errors"] += 1
        elif outcome == "rejected":
            st["rejected"] += 1
        lat = r.get("latency_s")
        if isinstance(lat, (int, float)):
            st["latencies"].append(float(lat))
    table = {}
    for t, st in sorted(tenants.items()):
        lats = sorted(st["latencies"])

        def pct(q):
            if not lats:
                return None
            i = min(len(lats) - 1, int(math.ceil(q * len(lats))) - 1)
            return round(lats[max(0, i)], 6)

        scored = max(1, st["requests"] - st["rejected"])
        table[t] = {
            "requests": st["requests"],
            "ok": st["ok"],
            "errors": st["errors"],
            "rejected": st["rejected"],
            "availability": round(1.0 - st["errors"] / scored, 6),
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
        }
    return table
