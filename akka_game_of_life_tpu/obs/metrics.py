"""Thread-safe runtime metrics — counters, gauges, fixed-bucket histograms.

The reference system's only runtime signal is its log stream; this registry
is the first-class replacement: every hot and failure path (stepper chunks,
peer retries, chaos crashes, checkpoint IO) records into named instruments,
and the whole registry renders as Prometheus text exposition (format 0.0.4)
— dumped to ``--metrics-file`` on exit and served live at ``/metrics`` by
:mod:`akka_game_of_life_tpu.obs.httpd`.

Design points:

- One lock per registry, taken only for child-creation and rendering;
  increments hit per-instrument locks (counters are on hot-ish paths — the
  retry loop, per-chunk accounting — but never inside jitted code).
- Instruments are created idempotently: ``registry.counter(name)`` returns
  the existing counter if the name is known, so instrumentation sites never
  need to coordinate registration order.
- Labeled instruments follow the Prometheus child model:
  ``c.labels(mode="tile").inc()``.  Unlabeled instruments expose a sample
  even at zero; labeled ones expose HELP/TYPE headers until a child exists
  (so the catalog is visible in every scrape either way).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Mapping, Optional, Sequence, Tuple

# Latency buckets shared by the step/obs/checkpoint histograms: half-decade
# log spacing from 0.5 ms to 60 s — wide enough for a CPU-interpret chunk
# and fine enough to separate a 2 ms from a 5 ms TPU chunk.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NAME_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or any(ch not in _NAME_OK for ch in name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double-quote,
    and newline (in that order, so the backslash pass cannot re-escape)."""
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def format_value(v: float) -> str:
    """Render a sample value: integers without a trailing .0, infinities in
    Prometheus spelling."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v != int(v):
        return repr(v)
    return str(int(v))


def _labels_suffix(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


class _Child:
    """One (labelset, value) series of an instrument."""

    __slots__ = ("_lock", "_value", "touched")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0  # graftlint: guarded-by _lock
        # Ever mutated?  snapshot() filters on this, not the value — a gauge
        # that was set and legitimately returned to 0 is still reported.
        self.touched = False

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters are monotonic; cannot inc by {amount}")
        with self._lock:
            self._value += amount
            self.touched = True


class _GaugeChild(_Child):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self.touched = True

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self.touched = True

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount
            self.touched = True


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count", "exemplars")

    def __init__(self, buckets: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # graftlint: guarded-by _lock
        self.sum = 0.0  # graftlint: guarded-by _lock
        self.count = 0  # graftlint: guarded-by _lock
        # Last exemplar per bucket index: (value, labels dict) — the click-
        # through from a latency bucket to a concrete trace.  Sparse: only
        # observes that pass an exemplar populate it.
        self.exemplars: Dict[int, Tuple[float, Dict[str, str]]] = {}  # graftlint: guarded-by _lock

    @property
    def touched(self) -> bool:
        # graftlint: waive GL-LOCK01 -- GIL-atomic read of a monotonic int used only as the exposition filter; a stale read under-reports one scrape and the next corrects it
        return self.count > 0

    def observe(
        self, value: float, exemplar: Optional[Mapping[str, str]] = None
    ) -> None:
        with self._lock:
            i = 0
            for i, le in enumerate(self.buckets):  # noqa: B007
                if value <= le:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self.sum += value
            self.count += 1
            if exemplar:
                self.exemplars[i] = (value, dict(exemplar))

    def exemplar_snapshot(self) -> list:
        """Per-bucket exemplars as ``[{"le", "value", "labels"}]`` (newest
        per bucket), keyed by the bucket's upper bound — what ``/slo``
        serves so a p99 spike clicks through to its trace id."""
        with self._lock:
            items = sorted(self.exemplars.items())
        bounds = list(self.buckets) + [math.inf]
        return [
            {"le": format_value(bounds[i]), "value": v, "labels": labels}
            for i, (v, labels) in items
        ]

    def snapshot(self) -> dict:
        """Cumulative bucket counts keyed by upper bound, plus sum/count."""
        with self._lock:
            counts = list(self.counts)
            total, n = self.sum, self.count
        out, cum = {}, 0
        for le, c in zip(self.buckets, counts):
            cum += c
            out[le] = cum
        out[math.inf] = cum + counts[-1]
        return {"buckets": out, "sum": total, "count": n}


class _Instrument:
    """A named metric family: type, help text, label names, children."""

    def __init__(
        self,
        name: str,
        kind: str,
        help: str,
        labelnames: Tuple[str, ...],
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> None:
        self.name = _check_name(name)
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}  # graftlint: guarded-by _lock
        if not labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self.buckets)
        return _CounterChild() if self.kind == "counter" else _GaugeChild()

    def labels(self, **labels: str):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
        return child

    def remove(self, **labels: str) -> None:
        """Drop one labeled child from the exposition.  A departed label
        set (an evicted tenant, a drained peer) must not export forever —
        unbounded label cardinality is a memory leak.  Removing a counter
        child forfeits its monotonic history (rate() handles the reset);
        callers own that trade.  No-op when the child never existed."""
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    # Unlabeled convenience passthroughs -------------------------------------

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels(...)"
            )
        # graftlint: waive GL-LOCK01 -- the () child is created in __init__ and never replaced; a GIL-atomic dict read of an immortal key needs no lock on the hot inc() path
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(
        self, value: float, exemplar: Optional[Mapping[str, str]] = None
    ) -> None:
        self._default().observe(value, exemplar)

    @property
    def value(self) -> float:
        return self._default().value

    def series(self) -> Iterable[Tuple[Dict[str, str], object]]:
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            yield dict(zip(self.labelnames, key)), child


class MetricsRegistry:
    """A process- or component-scoped set of named instruments.

    ``counter``/``gauge``/``histogram`` are get-or-create: re-asking for a
    known name returns the existing instrument (mismatched type or labels
    raises, so two call sites cannot silently split a metric)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}  # graftlint: guarded-by _lock

    def _get_or_create(
        self,
        name: str,
        kind: str,
        help: str,
        labelnames: Sequence[str],
        buckets: Optional[Sequence[float]] = None,
    ) -> _Instrument:
        labelnames = tuple(labelnames)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if inst.kind != kind or inst.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name} already registered as {inst.kind}"
                        f"{inst.labelnames}; asked for {kind}{labelnames}"
                    )
                return inst
            if buckets is not None:
                buckets = tuple(sorted(float(b) for b in buckets))
                if not buckets:
                    raise ValueError("histogram needs at least one bucket")
            inst = _Instrument(name, kind, help, labelnames, buckets)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        return self._get_or_create(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        return self._get_or_create(name, "gauge", help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        return self._get_or_create(name, "histogram", help, labelnames, buckets)

    def names(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._instruments))

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def value(self, name: str, **labels: str) -> float:
        """Read one series' current value (0.0 for a never-touched labelset
        of a known instrument) — the test/assertion surface."""
        inst = self.get(name)
        if inst is None:
            raise KeyError(name)
        if labels or inst.labelnames:
            return inst.labels(**labels).value
        return inst.value

    # -- exposition ----------------------------------------------------------

    def render(self) -> str:
        """The whole registry in Prometheus text exposition format 0.0.4,
        families sorted by name, with HELP/TYPE headers for every family
        (including labeled families that have no series yet)."""
        lines = []
        with self._lock:
            families = [self._instruments[n] for n in sorted(self._instruments)]
        for inst in families:
            if inst.help:
                lines.append(f"# HELP {inst.name} {_escape_help(inst.help)}")
            lines.append(f"# TYPE {inst.name} {inst.kind}")
            for labels, child in inst.series():
                if inst.kind == "histogram":
                    snap = child.snapshot()
                    for le, cum in snap["buckets"].items():
                        bl = dict(labels)
                        bl["le"] = format_value(le)
                        lines.append(
                            f"{inst.name}_bucket{_labels_suffix(bl)} {cum}"
                        )
                    lines.append(
                        f"{inst.name}_sum{_labels_suffix(labels)} "
                        f"{repr(snap['sum']) if snap['sum'] else '0'}"
                    )
                    lines.append(
                        f"{inst.name}_count{_labels_suffix(labels)} "
                        f"{snap['count']}"
                    )
                else:
                    lines.append(
                        f"{inst.name}{_labels_suffix(labels)} "
                        f"{format_value(child.value)}"
                    )
        return "\n".join(lines) + "\n"

    def snapshot(self, nonzero_only: bool = True) -> Dict[str, object]:
        """A compact JSON-able view of every live series — the shape bench
        records embed so a throughput line carries its halo-bytes and
        span-latency context.  Counters/gauges map name (with a label
        suffix for labeled series) to value; histograms map to
        ``{"count", "sum"}``.  ``nonzero_only`` drops never-*touched*
        series (so the pre-installed catalog doesn't bloat every record) —
        a gauge that was set and legitimately returned to 0 stays in."""
        out: Dict[str, object] = {}
        with self._lock:
            families = [self._instruments[n] for n in sorted(self._instruments)]
        for inst in families:
            for labels, child in inst.series():
                if nonzero_only and not child.touched:
                    continue
                key = f"{inst.name}{_labels_suffix(labels)}"
                if inst.kind == "histogram":
                    snap = child.snapshot()
                    out[key] = {"count": snap["count"], "sum": snap["sum"]}
                else:
                    out[key] = child.value
        return out

    def write(self, path: str) -> None:
        """Dump the exposition atomically (tmp + rename): a scrape of the
        file never sees a torn write, matching the checkpoint store's
        durability idiom."""
        from akka_game_of_life_tpu.obs.ioutil import atomic_write_text

        atomic_write_text(path, self.render(), prefix=".metrics_")


_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use, with the
    standard catalog installed so every exposition shows the full metric
    surface — zeros included)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            from akka_game_of_life_tpu.obs.catalog import install

            _GLOBAL = MetricsRegistry()
            install(_GLOBAL)
        return _GLOBAL
