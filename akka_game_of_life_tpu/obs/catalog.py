"""The standard metric catalog — every metric the runtime exports, declared
in one place.

This is the single source of truth three consumers share:

- :func:`install` pre-registers every family into a registry, so a scrape
  (or a ``--metrics-file`` dump) shows the full metric surface even for
  paths that never fired in this process — a standalone run still exposes
  ``gol_peer_retries_total 0``;
- ``docs/OPERATIONS.md`` documents the same names (the "Metrics & events"
  table);
- ``tools/check_metrics_doc.py`` (driven by a tier-1 test) asserts the two
  cannot drift: every name here AND every ``gol_*`` literal in the source
  must appear in the doc.

Naming follows Prometheus conventions: ``_total`` counters, ``_seconds``
histograms, bare gauges; everything is prefixed ``gol_``.
"""

from __future__ import annotations

from akka_game_of_life_tpu.obs.metrics import DEFAULT_BUCKETS, MetricsRegistry

# Rings-per-frame buckets for gol_ring_batch_size: batch sizes are small
# integer counts, not latencies, so the shared latency buckets would bin
# everything into one bucket.
RING_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)

# First-call (compile) seconds for gol_compile_seconds: XLA compiles run
# milliseconds to minutes, far past the request-latency buckets.
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0)

# (name, kind, help, labelnames[, buckets]) — histograms use DEFAULT_BUCKETS
# unless an entry carries its own.
CATALOG = (
    # -- simulation hot path (L3) --------------------------------------------
    ("gol_epochs_advanced_total", "counter",
     "Generations advanced by the local simulation loop", ()),
    ("gol_chunks_total", "counter",
     "Stepper chunks dispatched (one device round-trip each)", ()),
    ("gol_step_seconds", "histogram",
     "Wall seconds per stepper chunk (dispatch to board swap)", ()),
    ("gol_obs_seconds", "histogram",
     "Wall seconds per cadence observation (device dispatch + host fetch)",
     ()),
    ("gol_epoch", "gauge", "Current simulation epoch", ()),
    ("gol_population", "gauge", "Last observed live-cell population", ()),
    ("gol_steps_per_second", "gauge",
     "Epochs per wall second over the last observed interval", ()),
    ("gol_halo_bytes_total", "counter",
     "Halo bytes exchanged over the device mesh (analytic, per chunk)", ()),
    # -- cluster data/control plane (L1/L2) ----------------------------------
    ("gol_peer_sends_total", "counter",
     "Peer data-plane messages sent (rings, pulls, hellos)", ()),
    ("gol_peer_receives_total", "counter",
     "PEER_RING messages received from peer workers", ()),
    ("gol_peer_retries_total", "counter",
     "Stale-halo re-pulls fired by the retry loop (one per stale tile "
     "per round; rounds are gol_retry_wakeups_total)", ()),
    ("gol_retry_wakeups_total", "counter",
     "Retry-loop passes that found at least one stale tile", ()),
    ("gol_peer_drops_total", "counter",
     "Peer channels dropped (dead or stale-address peers)", ()),
    ("gol_heartbeats_total", "counter", "Heartbeats sent to the frontend", ()),
    ("gol_gather_failures_total", "counter",
     "GATHER_FAILED escalations sent after the retry budget", ()),
    ("gol_ring_bytes_total", "counter",
     "Boundary-ring payload bytes pushed to remote peers (dense cell "
     "bytes, whatever the wire encoding)", ()),
    ("gol_ring_packed_bytes_total", "counter",
     "Boundary-ring bytes actually put on the wire (bit-packed for binary "
     "rules when ring_pack is on; ratio to gol_ring_bytes_total is the "
     "packing win)", ()),
    ("gol_ring_batch_size", "histogram",
     "Rings coalesced into each PEER_RING_BATCH frame (count = frames "
     "sent)", (), RING_BATCH_BUCKETS),
    ("gol_peer_send_queue_depth", "gauge",
     "Entries queued in a peer's async send lane", ("peer",)),
    ("gol_peer_send_queue_drops_total", "counter",
     "Ring/ask entries dropped oldest-first by a full peer send queue "
     "(recovered via halo re-pulls)", ()),
    ("gol_members_alive", "gauge", "Cluster members currently alive", ()),
    ("gol_members_joined_total", "counter", "Workers that ever joined", ()),
    ("gol_members_lost_total", "counter",
     "Workers lost (EOF, stale heartbeat, or GOODBYE)", ()),
    ("gol_redeploys_total", "counter",
     "Tile redeployments (crash recovery, stuck escalation, node loss)", ()),
    # -- elastic plane: live migration, scale-out, drain (PR 6) ---------------
    ("gol_member_heartbeat_age_seconds", "gauge",
     "Seconds since each member's last control-plane traffic (staleness "
     "early warning; auto-down fires at failure_timeout_s)", ("member",)),
    ("gol_members_draining", "gauge",
     "Members currently draining (graceful scale-in in progress)", ()),
    ("gol_migrations_total", "counter",
     "Live tile migrations committed (digest-certified ownership moves)", ()),
    ("gol_migration_aborts_total", "counter",
     "Live tile migrations rolled back (digest mismatch, deadline, or "
     "member loss — the source kept the tile, no epoch lost)", ()),
    ("gol_migration_seconds", "histogram",
     "Wall seconds per committed migration (PREPARE to COMMIT)", ()),
    ("gol_drains_total", "counter",
     "Graceful worker drains completed (every tile migrated off before "
     "the member left)", ()),
    # -- multi-tenant serving plane (serve/) ----------------------------------
    ("gol_serve_sessions", "gauge",
     "Live board sessions, per tenant", ("tenant",)),
    ("gol_serve_cells", "gauge",
     "Aggregate live-session cells (the serve_max_cells admission "
     "resource)", ()),
    ("gol_serve_session_creates_total", "counter",
     "Board sessions admitted, per tenant", ("tenant",)),
    ("gol_serve_session_evictions_total", "counter",
     "Sessions evicted by the idle TTL sweep", ()),
    ("gol_serve_steps_total", "counter",
     "Board generations served, per tenant", ("tenant",)),
    ("gol_serve_rejects_total", "counter",
     "Requests refused by admission control (HTTP 429), by reason",
     ("reason",)),
    ("gol_serve_queue_depth", "gauge",
     "Step jobs pending in the engine queue", ()),
    ("gol_serve_batch_boards", "histogram",
     "Boards advanced per batched device program (count = programs run)",
     (), RING_BATCH_BUCKETS),
    ("gol_serve_tick_seconds", "histogram",
     "Wall seconds per engine tick (batch assembly + device programs + "
     "scatter-back)", ()),
    ("gol_serve_step_seconds", "histogram",
     "Wall seconds per step request, enqueue to result (queue wait + "
     "batch run)", ()),
    ("gol_serve_ff_jumps_total", "counter",
     "Serve fast-path jumps committed (linear-rule sessions stepping "
     "past serve_max_steps via O(log T) fast-forward)", ()),
    ("gol_serve_ff_jump_retries_total", "counter",
     "Fast-path optimistic commits that lost the race to a batched "
     "write-back and recomputed (bounded; the PR 12 residue, observable)",
     ()),
    # -- cluster-sharded serving (serve/cluster.py + serve/worker.py) ---------
    ("gol_serve_shards", "gauge",
     "Session shards owned, per serve worker (reclaimed to 0 on loss)",
     ("member",)),
    ("gol_serve_shard_sessions", "gauge",
     "Sessions resident, per serve worker (reclaimed to 0 on loss)",
     ("member",)),
    ("gol_serve_worker_queue_depth", "gauge",
     "Serve ops in flight toward each worker (unsent + unanswered; "
     "reclaimed to 0 on loss)", ("member",)),
    ("gol_serve_ops_total", "counter",
     "Session ops forwarded to workers by the cluster frontend", ()),
    ("gol_serve_op_frames_total", "counter",
     "SERVE_OPS frames sent (ops_total / op_frames_total = the op-plane "
     "coalescing ratio)", ()),
    ("gol_serve_shard_migrations_total", "counter",
     "Session-shard migrations committed (freeze → certify → commit)", ()),
    ("gol_serve_shard_migration_aborts_total", "counter",
     "Session-shard migrations rolled back (source unfroze, no loss)", ()),
    ("gol_serve_tiled_sessions", "gauge",
     "Mega-board sessions admitted as tiled (above the largest size "
     "class, fanned across workers per chunk)", ()),
    # -- worker-resident tiled sessions (serve/cluster.py + serve/worker.py) --
    ("gol_serve_tiled_bytes_round", "histogram",
     "Cell-state bytes moved per tiled-session step round (resident "
     "mode: peer halo strips, O(perimeter); ship mode: full chunk "
     "payloads through the frontend, O(area))", (),
     (2**10, 2**12, 2**14, 2**16, 2**18, 2**20, 2**22, 2**24)),
    ("gol_serve_tiled_halo_bytes_total", "counter",
     "Peer-to-peer TILED_HALO strip payload bytes sent by this worker", ()),
    ("gol_serve_tiled_halo_retx_total", "counter",
     "TILED_HALO strips retransmitted after an ack timeout", ()),
    ("gol_serve_tiled_resident_chunks", "gauge",
     "Resident tiled-session chunks hosted by this worker", ()),
    ("gol_serve_tiled_chunk_migrations_total", "counter",
     "Resident tiled chunks re-homed digest-certified (drain/load "
     "rebalancing)", ()),
    # -- session replication & failover (serve/cluster.py) --------------------
    ("gol_serve_replication_lag_seconds", "gauge",
     "Age of the oldest session update the shard's replica has not yet "
     "acked, per shard (0 = caught up; defined only while a replica "
     "exists, reclaimed when caught up/lost)", ("shard",)),
    ("gol_serve_replica_bytes_total", "counter",
     "Bit-packed session snapshot bytes relayed to replicas", ()),
    ("gol_serve_promotions_total", "counter",
     "Shard replicas promoted to primary after a worker loss "
     "(digest-certified; sessions resumed at their replicated epoch)",
     ()),
    ("gol_serve_single_copy_shards", "gauge",
     "Owned shards with NO placeable replica — the honest single-copy "
     "degradation level (0 when replication is healthy)", ()),
    ("gol_serve_sessions_lost_total", "counter",
     "Sessions lost to worker failure (no replica, never-acked, or a "
     "double failure) — each one is a tenant-visible 404", ()),
    # -- frontend federation (serve/federation.py) ----------------------------
    ("gol_frontend_peers", "gauge",
     "Live federation peer frontends (connected AND gossip-fresh)", ()),
    ("gol_frontend_gossip_age_seconds", "gauge",
     "Seconds since the last frame from each peer frontend (label "
     "reclaimed when the peer is confirmed dead)", ("peer",)),
    ("gol_frontend_forwarded_ops_total", "counter",
     "Serve ops forwarded to the owning peer frontend over the peer "
     "link (P_FWD_OPS)", ()),
    ("gol_frontend_forward_redirects_total", "counter",
     "Fat-payload requests answered with a 307 to the owning frontend "
     "instead of proxied (GET /boards/<id>)", ()),
    ("gol_frontend_slice_promotions_total", "counter",
     "Slices adopted from a confirmed-dead peer frontend by its "
     "rendezvous standby", ()),
    ("gol_frontend_slices_owned", "gauge",
     "Serve-keyspace slices this frontend currently owns", ()),
    ("gol_frontend_parked_ops_total", "counter",
     "Ops parked with retryable 429 'partitioned' because the owning "
     "frontend is suspect but not provably dead (the split-brain guard)",
     ()),
    ("gol_frontend_replicated_rows_total", "counter",
     "Control-state rows streamed to this frontend's standby peer "
     "(P_REPLICATE)", ()),
    # -- per-tenant SLO plane (obs/slo.py, served at /slo) --------------------
    ("gol_serve_slo_requests_total", "counter",
     "HTTP requests against the serve surface, per tenant/route/outcome "
     "(ok | rejected | client_error | error) — the SLO plane's R+E",
     ("tenant", "route", "outcome")),
    ("gol_serve_slo_latency_seconds", "histogram",
     "End-to-end request latency per tenant (trace-id exemplars ride the "
     "buckets: a p99 spike clicks through to a concrete trace via /slo)",
     ("tenant",)),
    ("gol_serve_slo_queue_wait_seconds", "histogram",
     "Worker-side queue wait per step request (relayed to the edge; "
     "latency minus this is compute + wire)", ()),
    ("gol_serve_slo_burn_rate", "gauge",
     "Error-budget burn rate per objective (availability | latency) and "
     "window (fast | slow); 1.0 = burning exactly the budget",
     ("objective", "window")),
    ("gol_serve_slo_burn_alert", "gauge",
     "1 while the multi-window burn alert is firing for an objective "
     "(both windows past threshold), else 0", ("objective",)),
    ("gol_serve_slo_alerts_total", "counter",
     "Burn-alert firing edges per objective (transition-edged: one per "
     "incident, not per scrape)", ("objective",)),
    ("gol_serve_slo_tenants", "gauge",
     "Tenants currently tracked by the SLO plane (LRU-bounded by "
     "serve_slo_max_tenants; evictees fold into the ~overflow tenant)",
     ()),
    # -- digest-certified canary prober (serve/canary.py) ---------------------
    ("gol_canary_probes_total", "counter",
     "Canary probes by outcome (ok | mismatch | rejected | lost | error "
     "| pin_failed) — the black-box availability numerator/denominator",
     ("outcome",)),
    ("gol_canary_failures_total", "counter",
     "Canary probes that PAGED: digest mismatch against the numpy "
     "oracle, or a wedged/errored worker (flight dump reason="
     "canary_fail carries the failing trace)", ()),
    ("gol_canary_latency_seconds", "histogram",
     "Canary probe latency through the real HTTP surface (black-box; "
     "compare with gol_serve_slo_latency_seconds{tenant=\"canary\"})",
     ()),
    ("gol_canary_staleness_seconds", "gauge",
     "Seconds since the LEAST-recently-certified pinned session last "
     "certified ok (grows past the cadence = a worker is wedged or the "
     "surface is down)", ()),
    ("gol_canary_sessions", "gauge",
     "Canary sessions currently pinned (one per serving worker on the "
     "cluster plane)", ()),
    # -- cross-tenant memoized macro-stepping (serve/memo.py) -----------------
    ("gol_serve_memo_hits_total", "counter",
     "Macro-cell cache hits per tenant (zero-block shortcuts included: "
     "a dead tile is a free hit)", ("tenant",)),
    ("gol_serve_memo_misses_total", "counter",
     "Macro-cell cache misses per tenant (each unique miss costs one "
     "slot in the round's batched device call)", ("tenant",)),
    ("gol_serve_memo_epochs_total", "counter",
     "Epochs advanced through memoized macro-rounds per tenant (the "
     "fast-path share of gol_serve_steps_total)", ("tenant",)),
    ("gol_serve_memo_entries", "gauge",
     "Macro-cell cache entries resident (shared across all tenants)",
     ()),
    ("gol_serve_memo_bytes", "gauge",
     "Macro-cell cache bytes resident (bounded by serve_memo_max_mb)",
     ()),
    ("gol_serve_memo_evictions_total", "counter",
     "Macro-cell cache LRU evictions (byte budget pressure; an evicted "
     "block recomputes on next miss)", ()),
    ("gol_serve_memo_hit_rate", "gauge",
     "Global macro-cell cache hit rate since start (hits / probes); the "
     "cross-tenant sharing signal the runbook watches", ()),
    ("gol_serve_memo_disables_total", "counter",
     "Sessions adaptively retired from the memo plane (hit rate below "
     "serve_memo_hit_floor for serve_memo_disable_after rounds, or a "
     "certification mismatch)", ()),
    ("gol_memo_certify_total", "counter",
     "Sampled memo-vs-direct certifications run (every "
     "serve_memo_certify_every-th macro-round per session)", ()),
    ("gol_memo_certify_mismatches_total", "counter",
     "Memo-vs-direct digest mismatches — a kernel/cache bug signal: "
     "event + flight dump reason=memo_certify_mismatch, the direct "
     "board wins, the session leaves the memo plane", ()),
    # -- logarithmic fast-forward (ops/fastforward.py) ------------------------
    ("gol_ff_jumps_total", "counter",
     "Fast-forward jumps committed by Simulation.fast_forward", ()),
    ("gol_ff_epochs_total", "counter",
     "Epochs advanced via O(log T) fast-forward jumps", ()),
    ("gol_ff_seconds", "histogram",
     "Wall seconds per fast-forward jump (certify + jump + board swap)",
     ()),
    # -- activity-gated sparse stepping --------------------------------------
    ("gol_tiles_skipped_total", "counter",
     "Tile chunks skipped by quiescent cluster tiles (frontend-merged "
     "worker deltas — the cluster tier's O(activity) win)", ()),
    ("gol_tiles_quiescent", "gauge",
     "Tiles currently self-reporting quiescent (period 1 or 2)", ()),
    ("gol_tile_chunks_skipped_total", "counter",
     "Tile chunks this worker skipped as provably quiescent", ()),
    ("gol_ring_same_markers_total", "counter",
     "O(1)-byte same-ring markers published in place of ring payloads", ()),
    ("gol_ring_same_miss_total", "counter",
     "Same-ring markers whose referenced epoch was not in the local store "
     "(recovered by the dependent pull's re-ask — latency, never "
     "corruption)", ()),
    ("gol_sparse_active_blocks", "gauge",
     "Blocks the intra-tile activity gate considers live this chunk", ()),
    ("gol_sparse_blocks_stepped_total", "counter",
     "Block-chunks actually advanced by the gated kernel", ()),
    ("gol_sparse_blocks_skipped_total", "counter",
     "Block-chunks skipped as provably unchanged by the activity gate", ()),
    ("gol_sparse_dense_chunks_total", "counter",
     "Chunks the gate handed to the dense kernel (active fraction over "
     "sparse_threshold, or a board of unknown provenance)", ()),
    # -- network chaos plane / hardened comms (PR 3) ---------------------------
    ("gol_net_chaos_dropped_total", "counter",
     "Messages dropped by the network chaos policy (random drops + "
     "partition blocks, send and recv side)", ()),
    ("gol_net_chaos_delayed_total", "counter",
     "Messages delayed by the network chaos policy", ()),
    ("gol_net_chaos_duplicated_total", "counter",
     "Messages duplicated by the network chaos policy", ()),
    ("gol_net_chaos_reordered_total", "counter",
     "Messages held so the next send overtakes them", ()),
    ("gol_net_partitions_total", "counter",
     "Network partitions opened (scheduled or manual)", ()),
    ("gol_net_partition_heals_total", "counter",
     "Network partitions healed", ()),
    ("gol_breaker_state", "gauge",
     "Per-peer circuit breaker state (0=closed, 1=open, 2=half-open)",
     ("peer",)),
    ("gol_breaker_open_total", "counter",
     "Circuit breaker closed-to-open transitions", ()),
    ("gol_breaker_skipped_sends_total", "counter",
     "Peer sends refused by an open circuit breaker", ()),
    ("gol_retry_backoff_seconds", "histogram",
     "Backoff delay chosen per halo re-pull retry (decorrelated jitter)",
     ()),
    ("gol_degraded_mode", "gauge",
     "1 while the frontend is in partition-degraded mode", ()),
    ("gol_degraded_entries_total", "counter",
     "Times the frontend entered degraded mode", ()),
    # -- chaos / failure paths -----------------------------------------------
    ("gol_chaos_crashes_total", "counter",
     "Crashes fired by the chaos injector (any mode)", ()),
    ("gol_chaos_recovered_total", "counter",
     "Injected crashes recovered by checkpoint restore + replay "
     "(standalone runtime; cluster recovery surfaces as "
     "gol_redeploys_total)", ()),
    ("gol_chaos_replay_epochs_total", "counter",
     "Epochs recomputed during standalone crash-recovery replay", ()),
    # -- digest certification plane ------------------------------------------
    ("gol_digest_checks_total", "counter",
     "Board digests computed/merged (standalone cadence observation, "
     "frontend tile-digest merges, recovery-source certification)", ()),
    ("gol_digest_mismatches_total", "counter",
     "Digest comparisons that disagreed (corrupt recovery source / "
     "diverged state — always a fault, never expected)", ()),
    ("gol_digest_seconds", "histogram",
     "Wall seconds per digest compute+fetch (device) or merge (frontend)",
     ()),
    # -- checkpoint / durability ---------------------------------------------
    ("gol_checkpoint_saves_total", "counter",
     "Checkpoint saves made durable (full-board or finalized per-tile)", ()),
    ("gol_checkpoint_restores_total", "counter",
     "Checkpoint loads (resume, recovery, or inspection)", ()),
    ("gol_checkpoint_seconds", "histogram",
     "Checkpoint IO wall seconds", ("op",)),
    # -- profiling spans -----------------------------------------------------
    ("gol_span_seconds", "histogram",
     "profiling.timed() span wall seconds", ("span",)),
    # -- compile & device-cost observatory (obs/programs.py) ------------------
    ("gol_compile_seconds", "histogram",
     "First-call (compile) wall seconds per registered jitted program, "
     "per kernel family", ("family",), COMPILE_BUCKETS),
    ("gol_programs_live", "gauge",
     "Jitted programs on the ledger, per family (cluster-merged on the "
     "frontend; reclaimed with their last contributing member)",
     ("family",)),
    ("gol_program_invocations_total", "counter",
     "Invocations of registered jitted programs, per family", ("family",)),
    ("gol_program_device_seconds_total", "counter",
     "Host-observed seconds inside registered jitted programs, per "
     "family (async dispatch makes this a throughput lower bound)",
     ("family",)),
    ("gol_compile_storms_total", "counter",
     "Compile storms: NEW programs that compiled after warmup (each one "
     "stalled a live batch; an event + flight dump marks each)", ()),
    ("gol_device_bytes_in_use", "gauge",
     "Device memory currently allocated, per device (cluster members "
     "namespaced member:device; reclaimed on loss)", ("device",)),
    ("gol_device_peak_bytes_in_use", "gauge",
     "Device memory high-water mark since process start, per device",
     ("device",)),
    ("gol_profile_captures_total", "counter",
     "On-demand jax.profiler captures taken (POST /profile)", ()),
)


def install(registry: MetricsRegistry) -> MetricsRegistry:
    """Pre-register every cataloged family into ``registry`` (idempotent)."""
    for entry in CATALOG:
        name, kind, help, labelnames = entry[:4]
        if kind == "counter":
            registry.counter(name, help, labelnames)
        elif kind == "gauge":
            registry.gauge(name, help, labelnames)
        else:
            buckets = entry[4] if len(entry) > 4 else DEFAULT_BUCKETS
            registry.histogram(name, help, labelnames, buckets=buckets)
    return registry


def names() -> tuple:
    return tuple(entry[0] for entry in CATALOG)
