"""Shared ``--metrics-file`` dump policy — one helper for every role.

Three call sites used to hand-roll the same loop (the known cleanup from
PR 1): the standalone simulation's cadence hook, the frontend maintenance
loop's wall-clock refresh, and the backend's dump thread.  They share one
contract, so it lives here once:

- the write is the registry's atomic tmp+rename exposition dump;
- a write failure (ENOSPC blip, NFS hiccup, directory removed mid-run) must
  never abort or freeze the path it observes — warn ONCE per outage, keep
  retrying, and re-arm the warning after a success;
- a final best-effort dump on the way out, with the same containment.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class MetricsDumper:
    """Warn-once, failure-contained exposition dumps to one file.

    Thread-safe: the frontend calls :meth:`maybe` from its maintenance
    thread while :meth:`final` runs on the stopping thread; the backend runs
    :meth:`loop` on its own daemon thread.
    """

    def __init__(
        self,
        registry,
        path: str,
        *,
        interval_s: float = 5.0,
        label: str = "metrics-file",
        out=None,
    ) -> None:
        self.registry = registry
        self.path = path
        self.interval_s = interval_s
        self.label = label
        self._out = out  # None = stdout (print default)
        self._lock = threading.Lock()
        self._warned = False
        self._next_due = time.monotonic() + interval_s

    def _warn(self, e: OSError) -> None:
        print(
            f"{self.label} write failed (will keep retrying): {e}",
            file=self._out,
            flush=True,
        )

    def dump(self) -> bool:
        """One write attempt.  Returns True on success; on failure warns
        once per outage and returns False (never raises)."""
        try:
            self.registry.write(self.path)
        except OSError as e:
            with self._lock:
                warn = not self._warned
                self._warned = True
            if warn:
                self._warn(e)
            return False
        with self._lock:
            self._warned = False
        return True

    def maybe(self, now: Optional[float] = None) -> bool:
        """Interval-gated :meth:`dump` for callers with their own loop (the
        frontend maintenance thread).  Returns True if a write happened."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            if now < self._next_due:
                return False
            self._next_due = now + self.interval_s
        self.dump()
        return True

    def loop(self, stop: threading.Event) -> None:
        """Dump every ``interval_s`` until ``stop`` is set (the backend's
        dump-thread body)."""
        while not stop.wait(self.interval_s):
            self.dump()

    def start_thread(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(
            target=self.loop, args=(stop,), daemon=True, name="metrics-dump"
        )
        t.start()
        return t

    def final(self) -> bool:
        """Best-effort exit dump: always warns on failure (an exit snapshot
        failing is worth one line even mid-outage) and never raises — the
        teardown behind it must complete."""
        try:
            self.registry.write(self.path)
        except OSError as e:
            print(f"final {self.label} write failed: {e}", file=self._out, flush=True)
            return False
        return True
