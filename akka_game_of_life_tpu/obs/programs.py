"""Process-wide jit-program ledger: the compile & device-cost observatory.

Every cached program factory in the repo (the ``ops/`` kernel families,
``serve/batch.py``'s per-(class, length) batch programs, the runtime
backend's chunk programs) registers the callable it is about to cache
through :func:`registered_jit`.  The wrapper is the whole integration
surface — one line per factory site — and buys three things:

- **a program ledger**: which jitted programs exist (per ``family`` and
  ``key``), when each compiled, and what its first call cost — the
  compile bill that XLA otherwise hides inside a mysteriously slow call;
- **a live roofline**: each call's host-observed seconds plus the site's
  plan-priced cells/bytes/FLOPs accumulate into per-family cell-updates/s
  and arithmetic intensity, reported by :meth:`ProgramRegistry.cost_doc`
  against the recorded r3b headline (:data:`R3B_CELLS_PER_S`) — so
  ``/cost`` answers "how far off the known-good rate is this config?"
  without a bench round;
- **a compile-storm alarm**: after :meth:`ProgramRegistry.mark_warm`
  (the serve router calls it once its steady-state classes have all
  compiled), any NEW program compiling is the invisible p99 killer — a
  novel (class, length) pair stalling a whole ticker batch — and edges
  an event + flight-recorder dump (PR 2 machinery) the moment it happens.

Honesty note on "device seconds": per-call timing is host wall time
around the jitted call.  Under JAX async dispatch this is dispatch time
unless the caller blocks on the result (the runtime's chunk loops do;
the serve ticker does).  The ledger documents a *lower bound* on
throughput, not a device-counter truth — the on-demand profiler
(``POST /profile``) exists for the latter.

Federation: workers ship :data:`runtime.protocol.COST` frames built from
:meth:`summary` on a low cadence; the frontend feeds them to
:meth:`merge_remote` so its ``/programs``, ``/cost``, and ``/healthz``
show the cluster-merged ledger, and calls :meth:`forget_remote` on member
loss so gauge labels are reclaimed (the breaker-reset hygiene rule).

The registry is process-global (:func:`get_programs`) for the same reason
the metrics registry is: factory sites are module-level caches with no
config in scope.  Roles configure it (node name, event log, flight
recorder, enable/disable) at startup via :meth:`configure`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from akka_game_of_life_tpu.obs.metrics import get_registry

# The recorded r3b packed-stencil headline (artifacts/tpu_session_r3b):
# 1.56e12 cell-updates/s/chip at 65536² on a v5e — the roofline anchor
# every per-family rate in /cost is reported against.
R3B_CELLS_PER_S = 1.56e12

_COST_FIELDS = ("cells", "bytes", "flops")


# Cataloged-metric accessors: label names must match obs/catalog.py exactly
# (the registry refuses a mismatched re-registration), and passing them here
# keeps the ledger working even on a bare registry that never ran install().
def _g_programs_live(reg):
    return reg.gauge(
        "gol_programs_live", "Jitted programs registered, per family",
        ("family",),
    )


def _g_device(reg, name: str, help: str):
    return reg.gauge(name, help, ("device",))


def _c_family(reg, name: str, help: str):
    return reg.counter(name, help, ("family",))


def _h_compile(reg):
    from akka_game_of_life_tpu.obs.catalog import COMPILE_BUCKETS

    return reg.histogram(
        "gol_compile_seconds",
        "First-call (compile) wall seconds per jitted program",
        ("family",), buckets=COMPILE_BUCKETS,
    )


def stencil_cost(
    h: int,
    w: int,
    steps: int = 1,
    *,
    boards: int = 1,
    itemsize: int = 1,
    flops_per_cell: float = 18.0,
) -> dict:
    """Plan-priced per-call cost of a dense stencil program: ``boards``
    boards of ``h×w`` cells advanced ``steps`` generations per invocation.

    ``bytes`` prices the streaming minimum (one read + one write of the
    board per step at ``itemsize`` bytes/cell); ``flops_per_cell``
    defaults to the 3×3 neighbor-sum + rule-select budget (~18 int ops).
    Families with a real plan (banded matmul, packed kernels) should
    price from the plan instead of this helper.
    """
    cells = float(boards) * float(h) * float(w) * float(steps)
    return {
        "cells": cells,
        "bytes": 2.0 * float(boards) * float(h) * float(w) * itemsize * steps,
        "flops": flops_per_cell * cells,
    }


class ProgramRecord:
    """One jitted program: identity, compile bill, and running totals."""

    __slots__ = (
        "family", "key", "compile_s", "compile_started", "calls",
        "seconds", "cells", "bytes", "flops", "post_warm", "storm_fired",
    )

    def __init__(self, family: str, key: str, post_warm: bool) -> None:
        self.family = family
        self.key = key
        self.compile_s: Optional[float] = None
        self.compile_started = False
        self.calls = 0
        self.seconds = 0.0
        self.cells = 0.0
        self.bytes = 0.0
        self.flops = 0.0
        self.post_warm = post_warm
        self.storm_fired = False

    def to_dict(self) -> dict:
        return {
            "family": self.family,
            "key": self.key,
            "compile_seconds": self.compile_s,
            "calls": self.calls,
            "seconds": self.seconds,
            "cells": self.cells,
            "bytes": self.bytes,
            "flops": self.flops,
            "post_warm": self.post_warm,
        }


class ProgramRegistry:
    """The process-wide jit-program ledger (see module docstring)."""

    def __init__(self, *, node: Optional[str] = None, clock=time.perf_counter):
        self._lock = threading.Lock()
        self._clock = clock
        self._programs: Dict[Tuple[str, str], ProgramRecord] = {}
        # member -> last COST summary doc ({"families", "devices", ...})
        self._remote: Dict[str, dict] = {}
        # label sets currently exported on the device gauges, for reclaim
        self._device_labels: Dict[str, set] = {}  # owner ("" = local) -> labels
        self._warm = False
        self._storms = 0
        self.enabled = True
        self.node = node
        self._events = None
        self._flight = None
        self._metrics = None
        # Named cost sections: auxiliary planes (the serve memo cache)
        # publish their economics into /cost and COST frames through a
        # provider callable instead of the ledger knowing their shape.
        self._sections: Dict[str, Callable[[], dict]] = {}

    def _reg(self):
        return self._metrics if self._metrics is not None else get_registry()

    # -- role wiring ---------------------------------------------------------

    def configure(
        self,
        *,
        node: Optional[str] = None,
        events=None,
        flight=None,
        metrics=None,
        enabled: Optional[bool] = None,
    ) -> "ProgramRegistry":
        """Attach role context: node name (labels COST frames and storm
        dumps), an EventLog and FlightRecorder for storm alerts, the
        MetricsRegistry the gauges/counters land in (default: the process
        registry), and the ``obs_programs`` enable switch (disabling makes
        :func:`registered_jit` a pass-through for programs built after)."""
        with self._lock:
            if node is not None:
                self.node = node
            if events is not None:
                self._events = events
            if flight is not None:
                self._flight = flight
            if metrics is not None:
                self._metrics = metrics
            if enabled is not None:
                self.enabled = enabled
        return self

    def reset(self) -> None:
        """Forget everything (tests)."""
        with self._lock:
            self._programs.clear()
            self._remote.clear()
            self._device_labels.clear()
            self._warm = False
            self._storms = 0
            self.enabled = True
            self._events = None
            self._flight = None
            self._metrics = None
            self._sections.clear()

    # -- the one integration surface -----------------------------------------

    def wrap(
        self,
        family: str,
        key,
        fn: Callable,
        *,
        cost=None,
    ) -> Callable:
        """Register ``fn`` (a jitted callable a factory is about to cache)
        under ``(family, key)`` and return the instrumented callable.

        ``cost`` prices one invocation: a static dict with ``cells`` /
        ``bytes`` / ``flops`` keys (factory keys encode shapes, so the
        per-call cost is usually static), or a callable over the call's
        arguments returning one.  First call timing is recorded as the
        compile bill; every call adds host-observed seconds and priced
        work to the family totals.
        """
        if not self.enabled:
            return fn
        skey = key if isinstance(key, str) else repr(key)
        with self._lock:
            rec = self._programs.get((family, skey))
            if rec is None:
                rec = ProgramRecord(family, skey, post_warm=self._warm)
                self._programs[(family, skey)] = rec
                live = sum(
                    1 for f, _ in self._programs if f == family
                )
            else:
                live = None
        if live is not None:
            _g_programs_live(self._reg()).labels(family=family).set(live)

        def call(*args, **kwargs):
            with self._lock:
                first = not rec.compile_started
                if first:
                    rec.compile_started = True
            t0 = self._clock()
            out = fn(*args, **kwargs)
            dt = self._clock() - t0
            c = cost(*args, **kwargs) if callable(cost) else cost
            storm = False
            with self._lock:
                rec.calls += 1
                rec.seconds += dt
                if first:
                    rec.compile_s = dt
                if c:
                    rec.cells += float(c.get("cells", 0.0))
                    rec.bytes += float(c.get("bytes", 0.0))
                    rec.flops += float(c.get("flops", 0.0))
                if first and rec.post_warm and not rec.storm_fired:
                    rec.storm_fired = True
                    self._storms += 1
                    storm = True
            mreg = self._reg()
            _c_family(
                mreg, "gol_program_invocations_total",
                "Invocations of registered jitted programs",
            ).labels(family=family).inc()
            _c_family(
                mreg, "gol_program_device_seconds_total",
                "Host-observed seconds inside registered jitted programs",
            ).labels(family=family).inc(dt)
            if first:
                _h_compile(mreg).labels(family=family).observe(dt)
            if storm:
                self._emit_storm(rec)
            return out

        call.__wrapped__ = fn
        return call

    def _emit_storm(self, rec: ProgramRecord) -> None:
        self._reg().counter(
            "gol_compile_storms_total",
            "New programs compiled after warmup (each one stalled a batch)",
        ).inc()
        events, flight = self._events, self._flight
        if events is not None:
            try:
                events.emit(
                    "compile_storm",
                    family=rec.family,
                    key=rec.key,
                    compile_seconds=rec.compile_s,
                    node=self.node,
                )
            except Exception:  # noqa: BLE001 — alerting must not break the call
                pass
        if flight is not None:
            try:
                flight.dump("compile_storm", node=self.node)
            except Exception:  # noqa: BLE001
                pass

    # -- named cost sections -------------------------------------------------

    def register_section(
        self, name: str, provider: Callable[[], dict]
    ) -> None:
        """Attach a named cost section: ``provider()`` returns a flat dict
        of numbers that rides :meth:`summary` (so workers federate it in
        COST frames) and lands merged in :meth:`cost_doc`.  Re-registering
        a name replaces the provider (routers restart in-process under
        tests); :meth:`reset` clears them."""
        with self._lock:
            self._sections[name] = provider

    def sections_doc(self) -> Dict[str, dict]:
        """Every local section's current numbers.  A provider that raises
        reports an empty section — /cost must render whatever else it has."""
        with self._lock:
            providers = dict(self._sections)
        out: Dict[str, dict] = {}
        for name, provider in providers.items():
            try:
                out[name] = dict(provider())
            except Exception:  # noqa: BLE001 — reporting must never raise
                out[name] = {}
        return out

    def _merged_sections(self) -> Dict[str, dict]:
        """Cluster-merged sections: numeric fields sum across the local
        doc and every member's COST frame; ``hit_rate`` is recomputed from
        the merged hits/misses (a mean of ratios would weight a cold
        worker's 0.0 the same as a hot one's 0.9)."""
        merged: Dict[str, dict] = {}
        with self._lock:
            remotes = list(self._remote.values())
        docs = [self.sections_doc()] + [
            doc.get("sections") or {} for doc in remotes
        ]
        for sections in docs:
            for name, fields in sections.items():
                tot = merged.setdefault(name, {})
                for k, v in fields.items():
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        tot[k] = tot.get(k, 0) + v
        for tot in merged.values():
            if "hits" in tot and "misses" in tot:
                probes = tot["hits"] + tot["misses"]
                tot["hit_rate"] = tot["hits"] / probes if probes else 0.0
        return merged

    # -- warmup / storm state ------------------------------------------------

    def mark_warm(self) -> None:
        """Arm the storm detector: every program that exists now is the
        expected steady state; a NEW program compiling after this is a
        compile storm.  Idempotent."""
        with self._lock:
            self._warm = True

    @property
    def warm(self) -> bool:
        with self._lock:
            return self._warm

    @property
    def storms(self) -> int:
        with self._lock:
            return self._storms

    @property
    def programs_total(self) -> int:
        """Count of registered local programs — cheap enough to sample
        around a batch tick (the serve router's warm heuristic: a tick
        that ran jobs without growing this is steady state)."""
        with self._lock:
            return len(self._programs)

    # -- device-memory watermarks --------------------------------------------

    def refresh_device_gauges(
        self, stats: Optional[dict] = None, *, owner: str = ""
    ) -> dict:
        """Export ``device_memory_stats()``-shaped watermarks as the
        cataloged per-device gauges, reclaiming labels that disappeared
        for the same ``owner`` (``""`` = this process's devices; a member
        name namespaces a worker's devices as ``member:device``).
        Returns the stats it exported."""
        if stats is None:
            from akka_game_of_life_tpu.runtime import profiling

            stats = profiling.device_memory_stats()
        mreg = self._reg()
        in_use = _g_device(
            mreg, "gol_device_bytes_in_use", "Device memory currently allocated"
        )
        peak = _g_device(
            mreg, "gol_device_peak_bytes_in_use",
            "Device memory high-water mark since process start",
        )
        labels = set()
        for dev, s in stats.items():
            label = f"{owner}:{dev}" if owner else str(dev)
            labels.add(label)
            in_use.labels(device=label).set(float(s.get("bytes_in_use", 0)))
            peak.labels(device=label).set(
                float(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))
            )
        with self._lock:
            stale = self._device_labels.get(owner, set()) - labels
            self._device_labels[owner] = labels
        for label in stale:
            in_use.remove(device=label)
            peak.remove(device=label)
        return stats

    # -- cluster federation ---------------------------------------------------

    def merge_remote(self, member: str, doc: dict) -> None:
        """Fold one worker's COST frame into the cluster view: stash its
        family summary for /programs //cost, export its device watermarks
        as ``member:device`` gauge children, refresh the merged
        programs-live gauges."""
        with self._lock:
            self._remote[member] = dict(doc)
        self.refresh_device_gauges(doc.get("devices") or {}, owner=member)
        self._refresh_family_gauges()

    def forget_remote(self, member: str) -> None:
        """Member loss: drop its ledger contribution and reclaim every
        gauge child it owned."""
        with self._lock:
            self._remote.pop(member, None)
        self.refresh_device_gauges({}, owner=member)
        self._refresh_family_gauges()

    def _refresh_family_gauges(self) -> None:
        merged = self._merged_families()
        gauge = _g_programs_live(self._reg())
        for family, agg in merged.items():
            gauge.labels(family=family).set(agg["programs"])
        # Reclaim families that only a departed member contributed.
        exported = [labels.get("family") for labels, _ in gauge.series()]
        for fam in exported:
            if fam is not None and fam not in merged:
                gauge.remove(family=fam)

    # -- reporting ------------------------------------------------------------

    def family_summary(self) -> Dict[str, dict]:
        """Local per-family aggregates (what a COST frame carries)."""
        out: Dict[str, dict] = {}
        with self._lock:
            for rec in self._programs.values():
                agg = out.setdefault(
                    rec.family,
                    {
                        "programs": 0,
                        "compile_seconds": 0.0,
                        "calls": 0,
                        "seconds": 0.0,
                        "cells": 0.0,
                        "bytes": 0.0,
                        "flops": 0.0,
                    },
                )
                agg["programs"] += 1
                agg["compile_seconds"] += rec.compile_s or 0.0
                agg["calls"] += rec.calls
                agg["seconds"] += rec.seconds
                agg["cells"] += rec.cells
                agg["bytes"] += rec.bytes
                agg["flops"] += rec.flops
        return out

    def summary(self) -> dict:
        """The COST-frame / bench-record snapshot: node identity, warmth,
        storm count, per-family aggregates, device watermarks."""
        from akka_game_of_life_tpu.runtime import profiling

        with self._lock:
            node, warm, storms = self.node, self._warm, self._storms
        try:
            devices = profiling.device_memory_stats()
        except Exception:  # noqa: BLE001 — reporting must never raise
            devices = {}
        return {
            "node": node,
            "warm": warm,
            "storms": storms,
            "families": self.family_summary(),
            "devices": devices,
            "sections": self.sections_doc(),
        }

    def snapshot(self) -> dict:
        """The ``/programs`` document: every local program, plus each
        member's federated family summary."""
        with self._lock:
            programs = sorted(
                (rec.to_dict() for rec in self._programs.values()),
                key=lambda d: (d["family"], d["key"]),
            )
            remote = {m: dict(doc) for m, doc in self._remote.items()}
            node, warm, storms = self.node, self._warm, self._storms
        return {
            "node": node,
            "warm": warm,
            "storms": storms,
            "programs": programs,
            "members": remote,
        }

    def _merged_families(self) -> Dict[str, dict]:
        merged = self.family_summary()
        with self._lock:
            remotes = list(self._remote.values())
        for doc in remotes:
            for family, agg in (doc.get("families") or {}).items():
                tot = merged.setdefault(
                    family,
                    {
                        "programs": 0,
                        "compile_seconds": 0.0,
                        "calls": 0,
                        "seconds": 0.0,
                        "cells": 0.0,
                        "bytes": 0.0,
                        "flops": 0.0,
                    },
                )
                for k in (
                    "programs", "compile_seconds", "calls",
                    "seconds", "cells", "bytes", "flops",
                ):
                    tot[k] += agg.get(k, 0)
        return merged

    def cost_doc(self) -> dict:
        """The ``/cost`` document — the live roofline ledger: cluster-
        merged per-family cell-updates/s and arithmetic intensity against
        the r3b headline, plus every device's memory watermark."""
        families = {}
        for family, agg in sorted(self._merged_families().items()):
            seconds = agg["seconds"]
            rate = agg["cells"] / seconds if seconds > 0 else 0.0
            families[family] = {
                **agg,
                "cell_updates_per_s": rate,
                "arithmetic_intensity": (
                    agg["flops"] / agg["bytes"] if agg["bytes"] > 0 else 0.0
                ),
                "vs_r3b_headline": rate / R3B_CELLS_PER_S,
            }
        devices: Dict[str, dict] = {}
        try:
            from akka_game_of_life_tpu.runtime import profiling

            for dev, s in profiling.device_memory_stats().items():
                devices[str(dev)] = dict(s)
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            remotes = {m: dict(doc) for m, doc in self._remote.items()}
            storms = self._storms
            node, warm = self.node, self._warm
        for member, doc in remotes.items():
            storms += int(doc.get("storms") or 0)
            for dev, s in (doc.get("devices") or {}).items():
                devices[f"{member}:{dev}"] = dict(s)
        return {
            "node": node,
            "warm": warm,
            "headline_cells_per_s": R3B_CELLS_PER_S,
            "storms": storms,
            "families": families,
            "devices": devices,
            "sections": self._merged_sections(),
        }

    def health_summary(self) -> dict:
        """The compact block /healthz embeds: program counts, compile
        bill, storm count, per-member warmth."""
        fams = self._merged_families()
        with self._lock:
            members = {
                m: {
                    "warm": bool(doc.get("warm")),
                    "storms": int(doc.get("storms") or 0),
                    "programs": sum(
                        int(f.get("programs") or 0)
                        for f in (doc.get("families") or {}).values()
                    ),
                }
                for m, doc in self._remote.items()
            }
            storms = self._storms
        return {
            "programs": sum(f["programs"] for f in fams.values()),
            "compile_seconds": round(
                sum(f["compile_seconds"] for f in fams.values()), 6
            ),
            "storms": storms + sum(m["storms"] for m in members.values()),
            "families": {f: a["programs"] for f, a in sorted(fams.items())},
            "members": members,
        }


_GLOBAL = ProgramRegistry()
_GLOBAL_LOCK = threading.Lock()


def get_programs() -> ProgramRegistry:
    """The process-wide registry every factory site registers through."""
    return _GLOBAL


def registered_jit(family: str, key, fn: Callable, *, cost=None) -> Callable:
    """Module-level sugar for ``get_programs().wrap(...)`` — the one-line
    integration every cached jit-factory site uses (GL-HAZ05 enforces
    that they do)."""
    return _GLOBAL.wrap(family, key, fn, cost=cost)


def register_section(name: str, provider: Callable[[], dict]) -> None:
    """Module-level sugar for ``get_programs().register_section(...)``."""
    _GLOBAL.register_section(name, provider)


# -- HTTP surface -------------------------------------------------------------


def _query_param(path: str, name: str) -> Optional[str]:
    from urllib.parse import parse_qs, urlsplit

    vals = parse_qs(urlsplit(path).query).get(name)
    return vals[0] if vals else None


def http_routes(
    *,
    registry: Optional[ProgramRegistry] = None,
    profile: Optional[Callable[[Optional[float]], dict]] = None,
) -> dict:
    """The ``/programs`` + ``/cost`` (+ ``/profile`` when a capture
    callable is supplied) route table, mountable on any MetricsServer.

    ``profile(seconds)`` performs the capture and returns a JSON-ready
    dict; ``{"ok": False, "status": N}`` maps to that HTTP status (429
    rate-limited, 409 already running)."""
    from akka_game_of_life_tpu.obs.httpd import json_response

    reg = registry or get_programs()

    def programs_route(method, path, body):
        if method != "GET":
            return json_response(405, {"error": f"{method} /programs"})
        return json_response(200, reg.snapshot())

    def cost_route(method, path, body):
        if method != "GET":
            return json_response(405, {"error": f"{method} /cost"})
        return json_response(200, reg.cost_doc())

    routes = {"/programs": programs_route, "/cost": cost_route}

    if profile is not None:

        def profile_route(method, path, body):
            if method != "POST":
                return json_response(405, {"error": f"{method} /profile"})
            seconds: Optional[float] = None
            raw = _query_param(path, "seconds")
            if raw is None and body:
                import json as _json

                try:
                    doc = _json.loads(body.decode("utf-8"))
                    raw = doc.get("seconds") if isinstance(doc, dict) else None
                except (ValueError, UnicodeDecodeError):
                    return json_response(400, {"error": "body is not JSON"})
            if raw is not None:
                try:
                    seconds = float(raw)
                except (TypeError, ValueError):
                    return json_response(
                        400, {"error": f"seconds={raw!r} is not a number"}
                    )
            result = profile(seconds)
            status = 200 if result.get("ok") else int(
                result.get("status") or 429
            )
            return json_response(status, result)

        routes["/profile"] = profile_route

    return routes
