"""Structured JSONL event log — the machine-readable twin of the log stream.

Every lifecycle event the runtime emits (crash injected, crash recovered,
checkpoint saved, member joined/lost, redeploy, run start/end) becomes one
JSON object per line, with both a monotonic timestamp (``t_mono`` — ordering
and intervals survive wall-clock jumps) and a wall timestamp (``t_wall`` —
correlation across nodes), plus a per-node label so multi-process logs can
be merged and still attributed.

Enabled with ``--log-events PATH`` (appends, like the reference's info.log).
The writer is thread-safe (the frontend's reader threads and the simulation
loop both emit) and line-buffered: each event is flushed whole, so a crash
mid-run loses at most the event being written — never tears one.
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, List, Optional


class EventLog:
    """Append-only JSONL event sink with monotonic timestamps."""

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        node: str = "standalone",
        stream: Optional[IO[str]] = None,
        recorder=None,
    ) -> None:
        self.node = node
        # Optional flight-recorder tee: every emitted event also lands in the
        # crash ring buffer, so a post-mortem dump interleaves lifecycle
        # events with trace spans (obs/flight.py).  Tees even when the file
        # sink is disabled — the ring is cheap and the dump wants history.
        self.recorder = recorder
        self._lock = threading.Lock()
        self._own_file = None
        if stream is not None:
            self._out = stream
        elif path is not None:
            self._own_file = open(path, "a", encoding="utf-8")
            self._out = self._own_file
        else:
            self._out = None  # disabled: emit() is a no-op

    @property
    def enabled(self) -> bool:
        return self._out is not None

    def emit(self, event: str, /, **fields) -> None:
        """Write one event line.  ``fields`` must be JSON-serializable
        (non-serializable values degrade to ``str``); reserved keys
        (event/node/t_mono/t_wall) cannot be overridden."""
        if self._out is None and self.recorder is None:
            return
        rec = {
            "event": event,
            "node": self.node,
            "t_mono": time.monotonic(),
            "t_wall": time.time(),
        }
        for k, v in fields.items():
            if k not in rec:
                rec[k] = v
        if self.recorder is not None:
            self.recorder.record_event(rec)
        if self._out is None:
            return
        line = json.dumps(rec, separators=(",", ":"), default=str)
        with self._lock:
            if self._out is None:
                return
            self._out.write(line + "\n")
            self._out.flush()

    def close(self) -> None:
        with self._lock:
            if self._own_file is not None:
                self._own_file.close()
                self._own_file = None
            self._out = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# Shared disabled sink: callers hold an EventLog unconditionally and emit
# without guarding, paying one attribute check when logging is off.
NULL_EVENTS = EventLog(None)


def read_events(path: str) -> List[dict]:
    """Parse a JSONL event file back into dicts (the round-trip surface for
    tests and offline analysis).  Blank lines are skipped; a torn final line
    (crash mid-write) raises, by design — silent truncation would hide it."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
