"""Crash flight recorder — the last N spans + events, dumped on failure.

The reference's only post-mortem artifact is whatever info.log happened to
say before a JVM died.  This recorder keeps a bounded ring of the most
recent observability records (finished trace spans, teed via
:class:`~akka_game_of_life_tpu.obs.tracing.Tracer`, plus lifecycle events,
teed via :class:`~akka_game_of_life_tpu.obs.events.EventLog` and explicit
``record()`` calls) and writes the whole ring to
``<dir>/flightrec-<node>-<ts>-<seq>.json`` when something goes wrong:

- an injected crash (standalone chaos replay, cluster CRASH / CRASH_TILE);
- a supervision replay (frontend tile redeploy);
- a node-loss redeploy (member eviction);
- SIGTERM (``runtime/signals.flight_dump_on_signals``).

Every injected fault becomes a self-contained post-mortem file: the causal
span history right up to the fault, on the node that saw it.  Dumps are
rate-limited (per reason) and capped per process so a redeploy storm cannot
fill a disk; the write is atomic (tmp + rename) and never raises into the
failure path it is documenting.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import List, Optional

from akka_game_of_life_tpu.obs.ioutil import atomic_write_text

_NODE_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


class FlightRecorder:
    """Bounded in-memory ring of observability records with crash dumps.

    ``directory=None`` (or "") disables dumping — the ring still records,
    so a later :meth:`configure` (e.g. the CLI applying ``--flight-dir``)
    arms dumps with history already in the buffer.
    """

    def __init__(
        self,
        node: str = "proc",
        *,
        capacity: int = 512,
        directory: Optional[str] = "artifacts",
        max_dumps: int = 64,
        min_interval_s: float = 0.5,
        clock=time.monotonic,
        wallclock=time.time,
    ) -> None:
        self.node = node  # graftlint: guarded-by _lock
        self.directory = directory  # graftlint: guarded-by _lock
        self.max_dumps = max_dumps
        self.min_interval_s = min_interval_s
        self._clock = clock
        self._wall = wallclock
        # RLock, not Lock: the SIGTERM dump handler runs ON the main thread,
        # which may be inside record()/record_span() (every span finish on
        # the hot loop takes this lock) at the moment the signal lands — a
        # plain lock would deadlock the shutdown it decorates.
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=capacity)  # graftlint: guarded-by _lock
        self._seq = 0  # graftlint: guarded-by _lock
        self._dumps = 0  # graftlint: guarded-by _lock
        # reason -> monotonic time of last dump
        self._last_dump: dict = {}  # graftlint: guarded-by _lock
        self.dump_paths: List[str] = []  # graftlint: guarded-by _lock

    def configure(
        self, *, directory: Optional[str] = None, node: Optional[str] = None
    ) -> "FlightRecorder":
        """Late-bind the dump directory / node label (CLI config arrives
        after the process-global recorder exists)."""
        with self._lock:
            if directory is not None:
                self.directory = directory or None
            if node is not None:
                self.node = node
        return self

    @property
    def enabled(self) -> bool:
        with self._lock:
            return bool(self.directory)

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, /, **fields) -> None:
        """Append one record to the ring (never raises; non-serializable
        values degrade to ``str`` at dump time)."""
        rec = {
            "kind": kind,
            "t_mono": self._clock(),
            "t_wall": self._wall(),
        }
        for k, v in fields.items():
            if k not in rec:
                rec[k] = v
        with self._lock:
            self._ring.append(rec)

    def record_span(self, span) -> None:
        """Tee one finished tracer span into the ring (Tracer calls this)."""
        d = span.to_dict() if hasattr(span, "to_dict") else dict(span)
        d["kind"] = "span"
        with self._lock:
            self._ring.append(d)

    def record_event(self, event: dict) -> None:
        """Tee one EventLog record into the ring."""
        d = dict(event)
        d["kind"] = "event"
        with self._lock:
            self._ring.append(d)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # -- dumping -------------------------------------------------------------

    def dump(self, reason: str, *, node: Optional[str] = None) -> Optional[str]:
        """Write the ring to ``flightrec-<node>-<ts>-<seq>.json``.

        Returns the path, or None when disabled, rate-limited (same reason
        within ``min_interval_s``), or past the per-process dump cap.  Any
        write failure is swallowed after a one-line note: the recorder rides
        failure paths, and a full disk must not mask the original fault.
        """
        now = self._clock()
        with self._lock:
            if not self.directory or self._dumps >= self.max_dumps:
                return None
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return None
            self._last_dump[reason] = now
            self._dumps += 1
            self._seq += 1
            seq = self._seq
            records = list(self._ring)
            directory = self.directory
            node = node or self.node
        doc = {
            "node": node,
            "reason": reason,
            "dumped_t_wall": self._wall(),
            "dumped_t_mono": now,
            "records": records,
        }
        ts = int(doc["dumped_t_wall"] * 1000)
        fname = f"flightrec-{_NODE_SAFE.sub('_', node)}-{ts}-{seq:03d}.json"
        path = os.path.join(directory, fname)
        try:
            atomic_write_text(
                path, json.dumps(doc, default=str), prefix=".flightrec_"
            )
        except (OSError, TypeError, ValueError) as e:
            # TypeError/ValueError: a hostile record that json cannot
            # serialize even with default=str must not mask the fault
            # being documented.
            _note(f"flight-recorder dump failed: {e}")
            return None
        with self._lock:
            self.dump_paths.append(path)
        _note(f"flight recorder: {reason} -> {path}")
        return path


def _note(msg: str) -> None:
    """A print that cannot raise.  dump() runs inside signal handlers (the
    SIGTERM hook), where a write into a stdout buffer the interrupted main
    thread is mid-write on raises RuntimeError('reentrant call') — which
    would abort the chained graceful-shutdown handler.  Losing the note is
    the acceptable outcome; breaking the shutdown is not."""
    try:
        print(msg, flush=True)
    except (RuntimeError, OSError, ValueError):
        pass


def read_flight(path: str) -> dict:
    """Parse a flight-recorder dump back (the test/offline surface)."""
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
