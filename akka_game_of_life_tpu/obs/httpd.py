"""Live exposition endpoint: ``/metrics`` + ``/healthz`` + ``/trace``.

``--metrics-port N`` on the ``run``, ``frontend``, and ``backend`` roles
starts this server; ``curl localhost:N/metrics`` scrapes the registry in
Prometheus text format, ``curl localhost:N/healthz`` answers a one-line JSON
health document (HTTP 200 while the role considers itself healthy, 503 once
it does not — the shape load balancers and k8s probes expect), and
``curl localhost:N/trace`` returns the live span buffer as Chrome
trace-event / Perfetto JSON (open it in ui.perfetto.dev or
``chrome://tracing``) when a tracer is attached.

Stdlib-only (``http.server``), threaded, daemonized: a scrape can never
block the simulation loop, and an abandoned server cannot hold the process
open.  Port 0 binds an ephemeral port (tests); the bound port is on
``server.port``.

Response discipline: every endpoint renders its body fully — taking
whatever registry/tracer locks rendering needs — BEFORE the first header
byte is written, so no internal lock is ever held across a socket write to
a possibly-slow scraper, concurrent scrapes serialize only on the in-memory
render, and every response (including 404s) carries ``Content-Length``.

The default bind is ``0.0.0.0`` — deliberate: probes and scrapers reach a
containerized role over the pod/VM network, not loopback (the exporter
convention).  The endpoint is unauthenticated and ``/healthz`` includes
internal error strings, so on shared hosts either firewall the port or
pass ``host="127.0.0.1"`` when constructing :class:`MetricsServer`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from akka_game_of_life_tpu.obs.metrics import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Serve one registry's exposition (and one tracer's span buffer) until
    :meth:`close`."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        host: str = "0.0.0.0",
        health: Optional[Callable[[], dict]] = None,
        tracer=None,
    ) -> None:
        self.registry = registry
        self.tracer = tracer
        # Health contract: return a JSON-serializable dict; "ok" (default
        # True) picks the status code.  Exceptions read as unhealthy.
        self._health = health or (lambda: {"ok": True})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, code: int, ctype: str, body: bytes) -> None:
                # Headers + body only AFTER the body is a finished byte
                # string: rendering (and its locks) never overlaps the
                # socket write, and Content-Length is always exact.
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._respond(
                        200, CONTENT_TYPE, outer.registry.render().encode("utf-8")
                    )
                elif path == "/healthz":
                    try:
                        doc = dict(outer._health())
                    except Exception as e:  # noqa: BLE001 — report, not raise
                        doc = {"ok": False, "error": repr(e)}
                    self._respond(
                        200 if doc.get("ok", True) else 503,
                        "application/json",
                        (json.dumps(doc) + "\n").encode("utf-8"),
                    )
                elif path == "/trace" and outer.tracer is not None:
                    self._respond(
                        200,
                        "application/json",
                        outer.tracer.export_json().encode("utf-8"),
                    )
                else:
                    self._respond(
                        404,
                        "application/json",
                        (json.dumps({"error": f"no route {path}"}) + "\n").encode(
                            "utf-8"
                        ),
                    )

            def log_message(self, fmt, *args):  # scrapes must not spam stdout
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"metrics-http-{self.port}",
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
