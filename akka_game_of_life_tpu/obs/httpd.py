"""Live exposition endpoint: registered routes over one tiny HTTP server.

``--metrics-port N`` on the ``run``, ``frontend``, ``backend``, and
``serve`` roles starts this server.  The built-in routes:

- ``/metrics`` — the registry in Prometheus text format;
- ``/healthz`` — a one-line JSON health document (HTTP 200 while the role
  considers itself healthy, 503 once it does not — the shape load
  balancers and k8s probes expect);
- ``/trace`` — the live span buffer as Chrome trace-event / Perfetto JSON
  (when a tracer is attached).

Subsystems mount more: every route lives in one registered-routes table
keyed by path prefix (:meth:`MetricsServer.add_route`), dispatched by
longest matching prefix — the serving plane's ``/boards`` API
(:mod:`akka_game_of_life_tpu.serve.api`) rides the same server, the same
``_respond`` discipline, and the same port as the scrape endpoint instead
of growing a second listener or an if/elif chain here.

A route handler is ``handler(method, path, body) -> (status, content_type,
body_bytes)``; it must render its response fully (taking whatever locks it
needs) before returning.  Raising maps to a 500 with the error repr; a
method the handler rejects should return 405 itself.  ``path`` is the RAW
request path — query string included (``POST /profile?seconds=3`` reads
its parameter from it); routing matches on the query-stripped path, and
handlers that parse path segments use :func:`strip_query` first.

Stdlib-only (``http.server``), threaded, daemonized: a scrape can never
block the simulation loop, and an abandoned server cannot hold the process
open.  Port 0 binds an ephemeral port (tests); the bound port is on
``server.port``.

Response discipline: every endpoint renders its body fully — taking
whatever registry/tracer locks rendering needs — BEFORE the first header
byte is written, so no internal lock is ever held across a socket write to
a possibly-slow scraper, concurrent requests serialize only on the
in-memory render, and every response (including 404s) carries
``Content-Length``.

The default bind is ``0.0.0.0`` — deliberate: probes and scrapers reach a
containerized role over the pod/VM network, not loopback (the exporter
convention).  The endpoint is unauthenticated and ``/healthz`` includes
internal error strings, so on shared hosts either firewall the port or
pass ``host="127.0.0.1"`` when constructing :class:`MetricsServer`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Mapping, Optional, Tuple

from akka_game_of_life_tpu.obs.metrics import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
JSON_TYPE = "application/json"

# A request body larger than this is refused with 413 before being read
# into memory — no route here needs more than a small JSON document.
MAX_BODY_BYTES = 4 << 20

# handler(method, path, body) -> (status, content_type, body_bytes)
RouteHandler = Callable[[str, str, bytes], Tuple[int, str, bytes]]


def json_response(status: int, doc: dict) -> Tuple[int, str, bytes]:
    """The common route-handler return shape for JSON documents."""
    return status, JSON_TYPE, (json.dumps(doc) + "\n").encode("utf-8")


def strip_query(path: str) -> str:
    """The request path without its query string.  Handlers receive the
    raw path (query included, so parameterized routes can read it); any
    handler that parses path *segments* strips first."""
    return path.split("?", 1)[0]


class MetricsServer:
    """Serve one registry's exposition — and any registered routes — until
    :meth:`close`."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        host: str = "0.0.0.0",
        health: Optional[Callable[[], dict]] = None,
        tracer=None,
        routes: Optional[Mapping[str, RouteHandler]] = None,
    ) -> None:
        self.registry = registry
        self.tracer = tracer
        # Health contract: return a JSON-serializable dict; "ok" (default
        # True) picks the status code.  Exceptions read as unhealthy.
        self._health = health or (lambda: {"ok": True})
        self._routes: dict = {}
        self.add_route("/metrics", self._metrics_route)
        self.add_route("/healthz", self._healthz_route)
        if tracer is not None:
            self.add_route("/trace", self._trace_route)
        for prefix, handler in (routes or {}).items():
            self.add_route(prefix, handler)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # Per-socket-op deadline (StreamRequestHandler applies it via
            # settimeout): a client that declares a Content-Length and then
            # withholds the bytes must not pin this connection thread
            # forever — the stalled read raises and the connection closes.
            timeout = 30
            # HTTP/1.1 so clients can keep connections alive: every
            # response here carries an exact Content-Length (the _respond
            # invariant), which is the precondition.  A serving-plane
            # client stepping a board per tick would otherwise pay a TCP
            # setup per request.
            protocol_version = "HTTP/1.1"
            def _respond(
                self, code: int, ctype: str, body: bytes, headers=None
            ) -> None:
                # Headers + body only AFTER the body is a finished byte
                # string: rendering (and its locks) never overlaps the
                # socket write, and Content-Length is always exact.
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    # Optional extra headers (a 307's Location) from
                    # 4-tuple route returns.
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, method: str) -> None:
                path = self.path.split("?", 1)[0]
                handler = outer._route_for(path)
                if handler is None:
                    self._respond(
                        *json_response(404, {"error": f"no route {path}"})
                    )
                    return
                if self.headers.get("Transfer-Encoding"):
                    # Chunked bodies are not decoded here; treating one
                    # as empty would silently serve wrong defaults.  411
                    # tells the client to resend with a Content-Length.
                    self._respond(
                        *json_response(
                            411, {"error": "send a Content-Length; chunked "
                                  "bodies are not supported"}
                        )
                    )
                    return
                try:
                    # max(0, ·): a negative declared length must not turn
                    # into rfile.read(-1) — a read-until-EOF that pins
                    # this connection thread until the client closes.
                    length = max(
                        0, int(self.headers.get("Content-Length") or 0)
                    )
                except ValueError:
                    length = 0
                if length > MAX_BODY_BYTES:
                    self._respond(
                        *json_response(413, {"error": "body too large"})
                    )
                    return
                body = self.rfile.read(length) if length else b""
                try:
                    # Handlers get the RAW request path — query string
                    # included — so routes like POST /profile?seconds=N
                    # can read parameters; routing above matched on the
                    # stripped path.  Handlers that parse path segments
                    # must split off "?" themselves (see strip_query).
                    # Returns are (status, ctype, body) or, for routes
                    # that set extra headers (the federation's 307
                    # Location), (status, ctype, body, headers).
                    result = handler(method, self.path, body)
                except Exception as e:  # noqa: BLE001 — a route bug must
                    # not kill the connection thread silently
                    result = json_response(500, {"error": repr(e)})
                self._respond(*result[:3], result[3] if len(result) > 3 else None)

            def do_GET(self):  # noqa: N802 — http.server API
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802
                self._dispatch("POST")

            def do_DELETE(self):  # noqa: N802
                self._dispatch("DELETE")

            def log_message(self, fmt, *args):  # requests must not spam stdout
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"metrics-http-{self.port}",
        )
        self._thread.start()

    # -- route table ---------------------------------------------------------

    def add_route(self, prefix: str, handler: RouteHandler) -> None:
        """Register ``handler`` for ``prefix`` (an exact path or a subtree
        root: ``/boards`` also receives ``/boards/<id>/...``).  Longest
        registered prefix wins; re-registering a prefix replaces it."""
        if not prefix.startswith("/") or (prefix != "/" and prefix.endswith("/")):
            raise ValueError(f"route prefix must look like /name, got {prefix!r}")
        self._routes[prefix] = handler

    def _route_for(self, path: str) -> Optional[RouteHandler]:
        best = None
        # Snapshot: add_route() on a live server must not resize the dict
        # under a request thread's iteration.
        for prefix, handler in tuple(self._routes.items()):
            if path == prefix or path.startswith(prefix + "/"):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, handler)
        return best[1] if best else None

    # -- built-in routes -----------------------------------------------------

    def _metrics_route(self, method, path, body):
        if method != "GET":
            return json_response(405, {"error": f"{method} {path}"})
        return 200, CONTENT_TYPE, self.registry.render().encode("utf-8")

    def _healthz_route(self, method, path, body):
        if method != "GET":
            return json_response(405, {"error": f"{method} {path}"})
        try:
            doc = dict(self._health())
        except Exception as e:  # noqa: BLE001 — report, not raise
            doc = {"ok": False, "error": repr(e)}
        return json_response(200 if doc.get("ok", True) else 503, doc)

    def _trace_route(self, method, path, body):
        if method != "GET":
            return json_response(405, {"error": f"{method} {path}"})
        return 200, JSON_TYPE, self.tracer.export_json().encode("utf-8")

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
