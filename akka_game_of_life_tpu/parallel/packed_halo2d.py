"""2-D sharded bit-packed stepping: rows × word-columns over a device grid.

The 1-D row ring (:mod:`akka_game_of_life_tpu.parallel.packed_halo`) is the
right shape for a single v5e-8 slice (65536 rows / 8 devices = 8192-row
shards); this module completes the scale-out story for larger meshes and
pods: the packed (H, W/32) grid is tiled over a ("row", "col") mesh, rows
exchanged along the row axis and *whole 32-cell words* along the col axis.

The word halo is communication-avoiding at the bit level: a halo word's
outermost cell loses validity first (it lacks its own off-tile neighbor) and
the garbage front advances exactly one bit per step, so ``hw`` halo words on
each side stay valid at the interior boundary for up to ``32*hw - 1`` local
steps — one exchanged uint32 buys 31 steps.  The local stepping reuses the
*toroidal* :func:`bitpack.step_packed` on the halo-padded tile: its wraps
only ever corrupt the outermost halo rows/words, which are cut edges
(garbage-tolerant by construction), so the same kernel serves the toroidal
single-device path and this tile path — at constant shape, which keeps the
inner loop a ``lax.scan`` instead of per-step unrolled bodies.

Exchange order is the dense path's two phases (columns first, then rows of
the column-padded tile) so corner words ride along and 8-direction
connectivity costs 4 ppermutes per exchange (``parallel/halo.py``).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from akka_game_of_life_tpu.ops.bitpack import (
    LANE_BITS,
    step_packed,
    require_packed_support,
)
from akka_game_of_life_tpu.ops.rules import resolve_rule
from akka_game_of_life_tpu.parallel.halo import ring_shift
from akka_game_of_life_tpu.parallel.mesh import (
    COL_AXIS,
    GEN_SPEC,
    GRID_SPEC,
    ROW_AXIS,
)


def word_halo_width(steps: int) -> int:
    """Halo words per side needed for ``steps`` local steps: the garbage
    front moves 1 bit/step, so hw words survive 32*hw - 1 steps."""
    return (steps + LANE_BITS) // LANE_BITS


def _sharded_exchange_fn(
    mesh: Mesh,
    spec,
    step_one: Callable[[jax.Array], jax.Array],
    *,
    steps_per_call: int,
    halo_rows: int,
    check_tile: Callable[[jax.Array], None],
    steps_per_exchange: Optional[int] = None,
    local_advance: Optional[Callable[[jax.Array], jax.Array]] = None,
    halo_words: Optional[int] = None,
    check_vma: bool = True,
) -> Callable[[jax.Array], jax.Array]:
    """The shared two-phase halo-exchange loop over a grid mesh.

    Works on any array whose LAST TWO axes are (rows, word-cols) — the
    binary packed board (H, W/32) and the Generations plane stack
    (m, H, W/32) alike.  Per exchange: word-column ppermutes first, then
    rows of the column-padded tile (corner words ride along), then the
    local advance on the padded tile at constant shape.  All local stepping
    is *toroidal*: the wraps only ever corrupt the outermost halo
    rows/words, which are cut edges (their true neighbors live off-tile)
    and garbage-tolerant by construction; both garbage fronts move 1 cell
    per step, so the interior slice is exact.  Constant shapes keep the
    inner loop a scan — compile cost is one step, not s unrolled bodies.

    By default the local advance is ``steps_per_exchange`` applications of
    ``step_one`` and the halo is exactly as deep as the step count; the
    Pallas path (:mod:`..parallel.pallas_halo`) overrides ``local_advance``
    (whole Mosaic sweeps), ``halo_rows`` (VMEM-block-aligned, deeper than
    the step count), ``halo_words`` (0 on single-column meshes, where the
    sweep's in-kernel word roll is the true torus wrap), and ``check_vma``
    (the vma tracker cannot yet see through pallas_call's interpret-mode
    discharge).

    The scan carries the *padded* tile and refreshes only the halo strips
    in place (``.at[].set`` → donated dynamic-update-slices), rather than
    re-assembling ``concat(halo, tile, halo)`` and re-slicing the interior
    every exchange.  At 65536² with 64-row Mosaic halos those two copies
    were ~2 GB of extra HBM traffic per 64-generation exchange — ~25% on
    top of the sweep's own read+write, the bulk of the measured 1.32 vs
    1.82×10¹² sharded-vs-torus gap (BASELINE.md round-3).  The strips are
    always read from the carried tile's *interior* rows/words, so the
    initial padding's halo content is never observed.
    """
    s = steps_per_exchange if steps_per_exchange is not None else halo_rows
    if steps_per_call % s:
        raise ValueError(
            f"steps_per_call={steps_per_call} must be a multiple of the "
            f"{s} steps per exchange"
        )
    hr = halo_rows
    hw = word_halo_width(s) if halo_words is None else halo_words
    n_exchanges = steps_per_call // s
    if local_advance is None:

        def local_advance(padded: jax.Array) -> jax.Array:
            out, _ = jax.lax.scan(
                lambda p, _: (step_one(p), None), padded, None, length=s
            )
            return out

    def local(tile: jax.Array) -> jax.Array:
        check_tile(tile)
        h_loc, w_loc = tile.shape[-2], tile.shape[-1]
        pad_width = [(0, 0)] * (tile.ndim - 2) + [(hr, hr), (hw, hw)]

        def body(p, _):
            # Phase 1 — word columns; my west halo is my left neighbor's
            # easternmost INTERIOR words (cols -2hw:-hw of the padded tile).
            if hw:
                west = ring_shift(p[..., hr:-hr, -2 * hw : -hw], COL_AXIS, +1)
                east = ring_shift(p[..., hr:-hr, hw : 2 * hw], COL_AXIS, -1)
                p = p.at[..., hr : hr + h_loc, :hw].set(west)
                p = p.at[..., hr : hr + h_loc, hw + w_loc :].set(east)
            # Phase 2 — full-width rows (the col halos just refreshed on the
            # neighbor ride along, so corner words arrive valid).
            top = ring_shift(p[..., -2 * hr : -hr, :], ROW_AXIS, +1)
            bottom = ring_shift(p[..., hr : 2 * hr, :], ROW_AXIS, -1)
            p = p.at[..., :hr, :].set(top)
            p = p.at[..., hr + h_loc :, :].set(bottom)
            return local_advance(p), None

        padded, _ = jax.lax.scan(
            body, jnp.pad(tile, pad_width), None, length=n_exchanges
        )
        out = padded[..., hr:-hr, :]
        return out[..., hw:-hw] if hw else out

    mapped = jax.shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=check_vma
    )
    sharding = NamedSharding(mesh, spec)
    return jax.jit(mapped, in_shardings=sharding, out_shardings=sharding)


def sharded_packed2d_step_fn(
    mesh: Mesh,
    rule,
    *,
    steps_per_call: int = 1,
    halo_rows: int = 1,
) -> Callable[[jax.Array], jax.Array]:
    """A jitted multi-step advance of a 2-D-sharded packed board.

    ``halo_rows`` is both the row-halo depth and the number of local steps
    per exchange; the word-column halo width follows from it
    (:func:`word_halo_width`).
    """
    rule = resolve_rule(rule)
    require_packed_support(rule)
    s, hw = halo_rows, word_halo_width(halo_rows)

    def check(tile: jax.Array) -> None:
        h_loc, w_loc = tile.shape
        if h_loc < s:
            raise ValueError(f"per-shard tile has {h_loc} rows < halo rows {s}")
        if w_loc < hw:
            raise ValueError(
                f"per-shard tile has {w_loc} words < word halo {hw}; "
                f"use fewer column shards or fewer steps per exchange"
            )

    return _sharded_exchange_fn(
        mesh,
        GRID_SPEC,
        lambda p: step_packed(p, rule),
        steps_per_call=steps_per_call,
        halo_rows=halo_rows,
        check_tile=check,
    )


def sharded_gen_step_fn(
    mesh: Mesh,
    rule,
    *,
    steps_per_call: int = 1,
    halo_rows: int = 1,
) -> Callable[[jax.Array], jax.Array]:
    """Width-k sharded stepping for Generations bit planes: (m, H, W/32)
    with the tiny plane dim replicated and rows × word-columns tiled over
    the grid mesh.  Same two-phase exchange and garbage-front economics as
    :func:`sharded_packed2d_step_fn` — the refractory-decay planes update
    cell-locally, so the alive plane's 1-cell/step front bounds them too."""
    from akka_game_of_life_tpu.ops.bitpack_gen import n_planes, step_gen

    rule = resolve_rule(rule)
    s, hw = halo_rows, word_halo_width(halo_rows)
    m = n_planes(rule.states)

    def check(planes: jax.Array) -> None:
        if planes.shape[0] != m:
            raise ValueError(f"expected {m} planes for {rule.states} states")
        _, h_loc, w_loc = planes.shape
        if h_loc < s or w_loc < hw:
            raise ValueError(
                f"per-shard plane tile {(h_loc, w_loc)} too small for "
                f"{s} steps per exchange"
            )

    return _sharded_exchange_fn(
        mesh,
        GEN_SPEC,
        lambda p: step_gen(p, rule),
        steps_per_call=steps_per_call,
        halo_rows=halo_rows,
        check_tile=check,
    )


def shard_packed2d(packed: jax.Array, mesh: Mesh) -> jax.Array:
    h, words = packed.shape
    rows, cols = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    if h % rows or words % cols:
        raise ValueError(
            f"packed grid {(h, words)} not divisible by mesh {(rows, cols)}"
        )
    return jax.device_put(packed, NamedSharding(mesh, GRID_SPEC))
