"""Sharded Mosaic stepping: the Pallas temporal-blocking sweep inside shard_map.

The single-chip Pallas kernel (:mod:`akka_game_of_life_tpu.ops.pallas_stencil`)
measured 8.5x the XLA bitpack path on a real v5e (BASELINE.md); this module
carries that win to the multi-chip configuration.  The trick is the same
garbage-front argument the XLA 2-D path uses (``parallel/packed_halo2d.py``):
the *toroidal* sweep runs unchanged on a halo-padded tile, because its torus
wraps only ever corrupt the outermost halo rows/words — cut edges whose true
neighbors live off-tile and which the interior slice discards.  One Mosaic
kernel therefore serves both the single-device path and every mesh shape.

Communication-avoiding economics, per wire exchange:

- the row halo is ``p = block_rows // 2`` packed rows per side — sized so the
  padded tile stays a whole number of VMEM row blocks (``h_loc + 2p`` is a
  multiple of ``block_rows``), which is what lets the torus sweep's BlockSpec
  grid tile it exactly;
- a p-row halo of current-generation rows stays valid at the interior for p
  local steps (the garbage front advances one row per step), so each exchange
  buys up to ``p`` generations — ``g`` back-to-back sweeps of ``k`` steps,
  ``g*k <= p``.  At the default ``block_rows=128`` that is 64 generations per
  ppermute round, 8x deeper than the XLA packed path's default;
- along the column axis (only when the mesh has >1 column shard) whole uint32
  words are exchanged; ``hw`` halo words survive ``32*hw - 1`` steps
  (``packed_halo2d.word_halo_width``).

Reference capability note: this is the end point of SURVEY.md §2 strategy 1 —
the reference's one-actor-per-cell random scatter with ~18 network messages
per cell per epoch (``NextStateCellGathererActor.scala:32-45``) becomes one
4-ppermute halo round per 64 generations per tile, with all compute staged
through VMEM by Mosaic.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh

from akka_game_of_life_tpu.ops.pallas_stencil import (
    DEFAULT_STEPS_PER_SWEEP,
    _round_up8,
    auto_steps_per_sweep,
    packed_sweep_fn,
)
from akka_game_of_life_tpu.ops.bitpack import require_packed_support
from akka_game_of_life_tpu.ops.rules import resolve_rule
from akka_game_of_life_tpu.parallel.mesh import COL_AXIS, GRID_SPEC
from akka_game_of_life_tpu.parallel.packed_halo2d import (
    _sharded_exchange_fn,
    word_halo_width,
)

DEFAULT_BLOCK_ROWS = 128  # measured-best VMEM row block on v5e (BASELINE.md)


def plan_exchange(
    steps_per_call: int,
    block_rows: int,
    steps_per_sweep: Optional[int] = None,
) -> tuple:
    """Choose (k, g): sweep depth and sweeps per exchange.

    ``k`` defaults to the largest divisor of ``steps_per_call`` that is <=
    DEFAULT_STEPS_PER_SWEEP and keeps the sweep's halo blocks sublane-aligned
    (``block_rows % round_up8(k) == 0``); ``g`` is the largest divisor of the
    total sweep count with ``g*k <= block_rows // 2`` (the halo depth).
    """
    p = block_rows // 2
    if steps_per_sweep is None:
        k = auto_steps_per_sweep(
            steps_per_call, block_rows, cap=min(DEFAULT_STEPS_PER_SWEEP, p)
        )
    else:
        k = steps_per_sweep
        if steps_per_call % k:
            raise ValueError(
                f"steps_per_call={steps_per_call} not a multiple of "
                f"steps_per_sweep={k}"
            )
        if block_rows % _round_up8(k):
            raise ValueError(
                f"block_rows={block_rows} must be a multiple of "
                f"{_round_up8(k)} (steps_per_sweep={k} sublane-aligned)"
            )
        if k > p:
            raise ValueError(
                f"steps_per_sweep={k} exceeds the halo depth "
                f"block_rows//2={p}"
            )
    n_sweeps = steps_per_call // k
    g = max(d for d in range(1, n_sweeps + 1) if n_sweeps % d == 0 and d * k <= p)
    return k, g


def _wire_sharded_sweep(
    mesh: Mesh,
    spec,
    *,
    steps_per_call: int,
    block_rows: int,
    steps_per_sweep: Optional[int],
    make_sweep: Callable[[int], Callable],
    make_check: Callable[[int], Callable],
    to_carry=None,
    from_carry=None,
) -> Callable[[jax.Array], jax.Array]:
    """The shared body of the sharded Mosaic steppers: plan the exchange,
    size halos, wrap g back-to-back k-generation sweeps as the local
    advance, and wire it through the two-phase exchange loop.

    ``make_sweep(k)`` builds the Mosaic sweep at the planned depth;
    ``make_check(hw)`` builds the per-tile validator given the word halo.
    ``to_carry``/``from_carry`` adapt the padded tile to the sweep's carry
    type (the plane sweep takes a tuple of 2-D planes; identity for the
    binary board).

    check_vma=False everywhere: the vma tracker can't yet see through
    pallas_call's interpret-mode discharge (shift-by-literal mixes
    varying/unvarying operands and errors with "Primitive shift_left
    requires varying manual axes to match"); JAX's own error text
    prescribes this workaround.  Correctness does not lean on the checker
    — every mesh shape is oracle-tested against the dense single-device
    step (test_pallas_halo).
    """
    k, g = plan_exchange(steps_per_call, block_rows, steps_per_sweep)
    steps_per_exchange = k * g
    p = block_rows // 2
    hw = word_halo_width(steps_per_exchange) if mesh.shape[COL_AXIS] > 1 else 0
    sweep = make_sweep(k)

    def advance(padded: jax.Array) -> jax.Array:
        # g back-to-back Mosaic sweeps of k generations each.  The padded
        # tile is h_loc + 2p = h_loc + block_rows rows — a whole number of
        # VMEM row blocks, which the torus sweep's BlockSpec grid tiles
        # exactly.
        carry = padded if to_carry is None else to_carry(padded)
        out, _ = jax.lax.scan(lambda s, _: (sweep(s), None), carry, None, length=g)
        return out if from_carry is None else from_carry(out)

    jitted = _sharded_exchange_fn(
        mesh,
        spec,
        None,
        steps_per_call=steps_per_call,
        halo_rows=p,
        check_tile=make_check(hw),
        steps_per_exchange=steps_per_exchange,
        local_advance=advance,
        halo_words=hw,
        check_vma=False,
    )

    def fn(board: jax.Array) -> jax.Array:
        return jitted(board)

    fn.steps_per_exchange = steps_per_exchange
    fn.steps_per_sweep = k
    return fn


def sharded_pallas_step_fn(
    mesh: Mesh,
    rule,
    *,
    steps_per_call: int = 1,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: Optional[int] = None,
    vmem_limit_bytes: Optional[int] = None,
    interpret: bool = False,
) -> Callable[[jax.Array], jax.Array]:
    """A jitted multi-step advance of a (rows x cols)-sharded packed board
    where the local compute is the Mosaic temporal-blocking sweep.

    The board is (H, W/32) uint32 under ``GRID_SPEC``; per-shard tiles must
    be a whole number of ``block_rows`` tall.  ``interpret=True`` runs the
    Pallas kernel in interpret mode (CPU-testable, same numerics).
    """
    rule = resolve_rule(rule)
    require_packed_support(rule)

    def make_check(hw: int):
        def check(tile: jax.Array) -> None:
            h_loc, w_loc = tile.shape
            if h_loc % block_rows:
                raise ValueError(
                    f"per-shard tile height {h_loc} not a multiple of "
                    f"block_rows={block_rows}"
                )
            if hw and w_loc < hw:
                raise ValueError(
                    f"per-shard tile has {w_loc} words < word halo {hw}; "
                    f"use fewer column shards or fewer steps per exchange"
                )

        return check

    return _wire_sharded_sweep(
        mesh,
        GRID_SPEC,
        steps_per_call=steps_per_call,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        make_sweep=lambda k: packed_sweep_fn(
            rule,
            block_rows=block_rows,
            steps_per_sweep=k,
            interpret=interpret,
            vmem_limit_bytes=vmem_limit_bytes,
        ),
        make_check=make_check,
    )


def sharded_gen_pallas_step_fn(
    mesh: Mesh,
    rule,
    *,
    steps_per_call: int = 1,
    block_rows: Optional[int] = None,
    steps_per_sweep: Optional[int] = None,
    vmem_limit_bytes: Optional[int] = None,
    interpret: bool = False,
) -> Callable[[jax.Array], jax.Array]:
    """The sharded Mosaic sweep for bit-plane rules (Generations /
    WireWorld): a (m, H, W/32) plane stack under ``GEN_SPEC`` (plane dim
    replicated, rows × word-cols tiled), local compute = the per-plane-
    operand Pallas sweep (:func:`..ops.pallas_gen.gen_sweep_fn`).

    Same exchange plan and garbage-front economics as the binary
    :func:`sharded_pallas_step_fn` — the plane transition is cell-local
    (radius 1), so the alive plane's 1-cell/step validity front bounds
    every plane; per-shard plane tiles must be a whole number of
    ``block_rows`` tall."""
    import jax.numpy as jnp

    from akka_game_of_life_tpu.ops import pallas_gen
    from akka_game_of_life_tpu.ops.bitpack_gen import (
        _require_plane_support,
        n_planes,
    )
    from akka_game_of_life_tpu.parallel.mesh import GEN_SPEC

    rule = resolve_rule(rule)
    _require_plane_support(rule)
    m = n_planes(rule.states)
    if block_rows is None:
        block_rows = pallas_gen.DEFAULT_BLOCK_ROWS

    def make_check(hw: int):
        def check(tile: jax.Array) -> None:
            if tile.shape[0] != m:
                raise ValueError(
                    f"expected {m} planes for {rule.states} states"
                )
            _, h_loc, w_loc = tile.shape
            if h_loc % block_rows:
                raise ValueError(
                    f"per-shard plane tile height {h_loc} not a multiple of "
                    f"block_rows={block_rows}"
                )
            if hw and w_loc < hw:
                raise ValueError(
                    f"per-shard plane tile has {w_loc} words < word halo "
                    f"{hw}; use fewer column shards or fewer steps per "
                    f"exchange"
                )

        return check

    return _wire_sharded_sweep(
        mesh,
        GEN_SPEC,
        steps_per_call=steps_per_call,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        make_sweep=lambda k: pallas_gen.gen_sweep_fn(
            rule,
            block_rows=block_rows,
            steps_per_sweep=k,
            interpret=interpret,
            vmem_limit_bytes=vmem_limit_bytes,
        ),
        make_check=make_check,
        to_carry=lambda padded: tuple(padded[j] for j in range(m)),
        from_carry=lambda out: jnp.stack(out),
    )
