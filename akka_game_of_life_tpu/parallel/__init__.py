from akka_game_of_life_tpu.parallel.mesh import (  # noqa: F401
    COL_AXIS,
    GRID_SPEC,
    ROW_AXIS,
    factor_2d,
    grid_sharding,
    make_grid_mesh,
    shard_board,
)
from akka_game_of_life_tpu.parallel.halo import (  # noqa: F401
    exchange_halo,
    sharded_step_fn,
    validate_tile_shape,
)
from akka_game_of_life_tpu.parallel.packed_halo import (  # noqa: F401
    make_row_mesh,
    shard_packed,
    sharded_packed_step_fn,
)
from akka_game_of_life_tpu.parallel.packed_halo2d import (  # noqa: F401
    shard_packed2d,
    sharded_gen_step_fn,
    sharded_packed2d_step_fn,
    word_halo_width,
)
from akka_game_of_life_tpu.parallel.pallas_halo import (  # noqa: F401
    sharded_gen_pallas_step_fn,
    sharded_pallas_step_fn,
)
from akka_game_of_life_tpu.parallel.digest import (  # noqa: F401
    sharded_dense_digest_fn,
    sharded_gen_digest_fn,
    sharded_packed2d_digest_fn,
)
from akka_game_of_life_tpu.parallel import distributed  # noqa: F401
