"""Multi-host (pod-scale) runtime over the JAX distributed runtime.

The reference scales out by joining backend JVMs into an Akka cluster over
Netty TCP with gossip membership and a static seed node
(``application.conf:19-23``, ``Run.scala:56-65``).  At pod scale the
TPU-native analog is ``jax.distributed``: one process per host connects to a
coordinator over DCN, after which ``jax.devices()`` is the GLOBAL device
list and a mesh built over it spans hosts — XLA routes collectives over ICI
within a slice and over DCN across slices (SURVEY.md §2 "TPU-native
equivalent").

Usage (one process per host, same program on every host):

    from akka_game_of_life_tpu.parallel import distributed
    distributed.initialize("host0:8476", num_processes=4, process_id=rank)
    mesh = make_grid_mesh()                      # spans ALL hosts' chips
    arr = distributed.make_global_array(board, mesh)
    out = sharded_step_fn(mesh, "conway", steps_per_call=k)(arr)
    full = distributed.fetch(out)                # host copy, all shards

On a real TPU pod slice every argument of :func:`initialize` is
auto-detected from the TPU metadata — call it with no arguments.  On
CPU/GPU clusters (and the 2-process CPU dryrun test) pass them explicitly
or via the ``GOL_COORDINATOR`` / ``GOL_NUM_PROCESSES`` / ``GOL_PROCESS_ID``
environment variables — the moral equivalent of the reference's seed-node
address + argv port overlay (``Run.scala:27-32``).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from akka_game_of_life_tpu.parallel.mesh import GRID_SPEC

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Idempotent ``jax.distributed.initialize`` with env fallbacks.

    Returns True if this call performed the initialization, False if the
    runtime was already up (safe to call from every entry point).  Must run
    before any device query — the same touch-ordering rule the dryrun
    enforces (``__graft_entry__.dryrun_multichip``).
    """
    global _initialized
    if _initialized:
        return False
    coordinator_address = coordinator_address or os.environ.get("GOL_COORDINATOR")
    if num_processes is None and os.environ.get("GOL_NUM_PROCESSES"):
        num_processes = int(os.environ["GOL_NUM_PROCESSES"])
    if process_id is None and os.environ.get("GOL_PROCESS_ID"):
        process_id = int(os.environ["GOL_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    return True


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_initialized() -> bool:
    return _initialized


def process_info() -> tuple:
    """(process_index, process_count) — (0, 1) when not distributed."""
    return jax.process_index(), jax.process_count()


def make_global_array(
    board, mesh, spec: PartitionSpec = GRID_SPEC
) -> jax.Array:
    """Shard a host-replicated board onto a (possibly multi-host) mesh.

    Every process passes the same full board (deterministic initial
    conditions make that free — ``runtime/simulation.py:initial_board``);
    each materializes only the shards its own devices address, so no process
    ever holds more than its slice on device.  Works unchanged on a
    single-host mesh, where it is equivalent to ``shard_board``.
    """
    board = np.asarray(board)
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        board.shape, sharding, lambda idx: board[idx]
    )


def fetch(arr) -> np.ndarray:
    """Bring a (possibly non-fully-addressable) array to the host, whole.

    Single-host arrays copy directly; multi-host arrays are assembled with
    an all-gather across processes, so every host gets the full board (the
    render/checkpoint path's host copy)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def barrier(tag: str = "gol") -> None:
    """Cross-host sync point (checkpoint durability, orderly shutdown)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
