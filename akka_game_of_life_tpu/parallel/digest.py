"""Sharded digest fold: per-shard fingerprints merged by ``psum`` — the
mesh paths certify state without ever gathering a board.

Each device digests its local tile with its GLOBAL cell offsets (derived
from ``axis_index``) and the lane sums fold across the mesh with one
``psum`` — O(devices) scalar traffic over ICI, ~8 bytes to the host,
regardless of board size.  One builder per sharded layout:

- :func:`sharded_dense_digest_fn` — dense uint8 (H, W) over the 2-D
  ("row", "col") grid mesh (``parallel/halo.py``'s layout);
- :func:`sharded_packed2d_digest_fn` — bit-packed (H, W/32) uint32 words
  over the same grid mesh (``parallel/packed_halo2d.py``'s layout, which
  is ALSO the sharded Pallas path's layout — ``parallel/pallas_halo.py``
  steps the identical row×word-column sharding, so this one fold
  certifies both the bitpack and Mosaic kernels);
- :func:`sharded_gen_digest_fn` — (m, H, W/32) Generations/WireWorld bit
  planes over ``GEN_SPEC`` (plane dim replicated).

Every builder returns a jitted ``board -> (2,) uint32 lanes`` closure
whose value is bit-identical to the single-device/host digests in
:mod:`akka_game_of_life_tpu.ops.digest` — that equality IS the
cross-path certification contract, pinned by ``tests/test_digest.py``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from akka_game_of_life_tpu.ops.digest import (
    digest_dense,
    digest_packed,
    digest_planes,
)
from akka_game_of_life_tpu.parallel.mesh import (
    COL_AXIS,
    GEN_SPEC,
    GRID_SPEC,
    ROW_AXIS,
)

_AXES = (ROW_AXIS, COL_AXIS)


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` where available, the experimental spelling on
    older jax — the digest fold is the certification plane, so it must
    run on CPU test environments pinned to pre-``jax.shard_map`` releases
    as well as on the TPU image."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _origin(mesh: Mesh, tile_rows: int, tile_cols: int):
    """Per-shard global (row0, col0) from the mesh coordinates (traced)."""
    r0 = jax.lax.axis_index(ROW_AXIS) * tile_rows
    c0 = jax.lax.axis_index(COL_AXIS) * tile_cols
    return r0, c0


def sharded_dense_digest_fn(
    mesh: Mesh, shape: Tuple[int, int]
) -> Callable[[jax.Array], jax.Array]:
    """Digest of a GRID_SPEC-sharded dense (H, W) uint8 board."""
    h, w = shape
    rows, cols = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    th, tw = h // rows, w // cols

    def local(tile: jax.Array) -> jax.Array:
        r0, c0 = _origin(mesh, th, tw)
        return jax.lax.psum(digest_dense(tile, r0, c0, width=w), _AXES)

    mapped = _shard_map(local, mesh, GRID_SPEC, PartitionSpec())
    return jax.jit(
        mapped, in_shardings=NamedSharding(mesh, GRID_SPEC)
    )


def sharded_packed2d_digest_fn(
    mesh: Mesh, shape: Tuple[int, int]
) -> Callable[[jax.Array], jax.Array]:
    """Digest of a GRID_SPEC-sharded packed (H, W/32) uint32 board
    (bitpack AND sharded-Pallas kernels — same layout)."""
    h, w = shape
    rows, cols = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    th, tw = h // rows, (w // 32) // cols

    def local(tile: jax.Array) -> jax.Array:
        r0, wc0 = _origin(mesh, th, tw)
        return jax.lax.psum(digest_packed(tile, w, r0, wc0), _AXES)

    mapped = _shard_map(local, mesh, GRID_SPEC, PartitionSpec())
    return jax.jit(
        mapped, in_shardings=NamedSharding(mesh, GRID_SPEC)
    )


def sharded_gen_digest_fn(
    mesh: Mesh, shape: Tuple[int, int], states: int
) -> Callable[[jax.Array], jax.Array]:
    """Digest of GEN_SPEC-sharded (m, H, W/32) Generations bit planes."""
    from akka_game_of_life_tpu.ops.bitpack_gen import n_planes

    h, w = shape
    m = n_planes(states)
    rows, cols = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    th, tw = h // rows, (w // 32) // cols

    def local(planes: jax.Array) -> jax.Array:
        assert planes.shape[0] == m, (planes.shape, m)
        r0, wc0 = _origin(mesh, th, tw)
        return jax.lax.psum(digest_planes(planes, w, r0, wc0), _AXES)

    mapped = _shard_map(local, mesh, GEN_SPEC, PartitionSpec())
    return jax.jit(
        mapped, in_shardings=NamedSharding(mesh, GEN_SPEC)
    )
