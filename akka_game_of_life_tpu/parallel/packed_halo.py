"""Sharded bit-packed stepping: the 65536²-class multi-chip configuration.

The packed grid (H, W/32) is partitioned by *rows* over a 1-D device ring —
words stay whole, so the halo is k packed rows per direction per exchange,
moved with a single ``ppermute`` ring shift each way over ICI.  Horizontal
(cross-word, cross-torus) bit carries stay entirely local because every
shard holds full rows.  A k-row halo buys k local steps per exchange, the
same communication-avoiding trade as the dense path
(:mod:`akka_game_of_life_tpu.parallel.halo`).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from akka_game_of_life_tpu.ops.bitpack import step_padded_rows
from akka_game_of_life_tpu.ops.bitpack import require_packed_support
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule
from akka_game_of_life_tpu.parallel.halo import ring_shift

SHARD_AXIS = "shard"
PACKED_SPEC = PartitionSpec(SHARD_AXIS, None)


def make_row_mesh(n_devices: int = None, devices: Sequence[jax.Device] = None) -> Mesh:
    """A 1-D mesh over which packed rows are ring-sharded."""
    devices = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return jax.make_mesh((len(devices),), (SHARD_AXIS,), devices=devices)


def _step_row_padded(padded: jax.Array, rule: Rule) -> jax.Array:
    """(h+2, words) with 1-row halos → (h, words)."""
    return step_padded_rows(padded, rule)


def sharded_packed_step_fn(
    mesh: Mesh,
    rule,
    *,
    steps_per_call: int = 1,
    halo_width: int = 1,
) -> Callable[[jax.Array], jax.Array]:
    """A jitted multi-step advance of a row-sharded packed board."""
    rule = resolve_rule(rule)
    require_packed_support(rule)
    if steps_per_call % halo_width:
        raise ValueError(
            f"steps_per_call={steps_per_call} must be a multiple of "
            f"halo_width={halo_width}"
        )
    n_exchanges = steps_per_call // halo_width

    def local(tile: jax.Array) -> jax.Array:
        k = halo_width
        if tile.shape[0] < k:
            raise ValueError(
                f"per-shard tile has {tile.shape[0]} rows < halo width {k}; "
                f"use fewer shards or a smaller halo"
            )

        def body(t, _):
            # Exchange k halo rows each way, then take k local steps on the
            # shrinking slab: (h+2k) → (h) rows (the dense path's scheme).
            top = ring_shift(t[-k:], SHARD_AXIS, +1)
            bottom = ring_shift(t[:k], SHARD_AXIS, -1)
            padded = jnp.concatenate([top, t, bottom], axis=0)
            for _ in range(k):
                padded = _step_row_padded(padded, rule)
            return padded, None

        out, _ = jax.lax.scan(body, tile, None, length=n_exchanges)
        return out

    mapped = jax.shard_map(local, mesh=mesh, in_specs=PACKED_SPEC, out_specs=PACKED_SPEC)
    sharding = NamedSharding(mesh, PACKED_SPEC)
    return jax.jit(mapped, in_shardings=sharding, out_shardings=sharding)


def shard_packed(packed: jax.Array, mesh: Mesh) -> jax.Array:
    h = packed.shape[0]
    n = mesh.shape[SHARD_AXIS]
    if h % n:
        raise ValueError(f"{h} rows not divisible by {n} shards")
    return jax.device_put(packed, NamedSharding(mesh, PACKED_SPEC))
