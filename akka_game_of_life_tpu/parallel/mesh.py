"""Device-mesh construction for the 2-D grid decomposition.

Where the reference scatters one actor per cell across backend JVMs by
uniform-random placement with no locality (``BoardCreator.scala:33-36,65-70``),
the TPU build tiles the torus into one contiguous HBM-resident shard per
device over a 2-D ``jax.sharding.Mesh`` — so every Moore-halo exchange is a
nearest-neighbor ``ppermute`` hop over ICI instead of a random cross-node
network message.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROW_AXIS = "row"
COL_AXIS = "col"
GRID_SPEC = PartitionSpec(ROW_AXIS, COL_AXIS)
# Generations bit planes (m, H, W/32): tiny plane dim replicated, grid tiled.
GEN_SPEC = PartitionSpec(None, ROW_AXIS, COL_AXIS)


def factor_2d(n: int) -> Tuple[int, int]:
    """Factor a device count into the most-square (rows, cols) grid."""
    best = (n, 1)
    for r in range(1, int(math.isqrt(n)) + 1):
        if n % r == 0:
            best = (n // r, r)
    return best


def make_grid_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 2-D device mesh with axes ("row", "col").

    With ``shape=None`` the available devices are auto-factored as square as
    possible (8 devices → 4×2).  Single-device meshes (1×1) are valid and let
    the same sharded code path run unsharded.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = factor_2d(len(devices))
    rows, cols = shape
    if rows * cols != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {rows * cols} devices, have {len(devices)}"
        )
    return jax.make_mesh((rows, cols), (ROW_AXIS, COL_AXIS), devices=devices)


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """The canonical (H, W) grid sharding: H over rows, W over cols."""
    return NamedSharding(mesh, GRID_SPEC)


def shard_board(board, mesh: Mesh) -> jax.Array:
    """Place a (H, W) board onto the mesh, one contiguous tile per device.

    H and W must divide evenly by the mesh axes — tiles are equal-sized by
    construction (unlike the reference, whose random placement gives no
    balance guarantee at all).
    """
    h, w = board.shape[-2], board.shape[-1]
    rows, cols = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    if h % rows or w % cols:
        raise ValueError(
            f"board {(h, w)} not evenly divisible by mesh {(rows, cols)}"
        )
    return jax.device_put(board, grid_sharding(mesh))
