"""Halo exchange over the device mesh — the ICI-native replacement for the
reference's per-neighbor Akka messages.

One reference epoch costs each cell 8 ask + 8 reply network messages through
an ephemeral gatherer actor (``NextStateCellGathererActor.scala:32-45``).
Here the entire Moore-neighborhood exchange for a whole tile is two phases of
``lax.ppermute`` ring shifts inside the jitted step:

- phase 1 shifts boundary *rows* along the mesh "row" axis;
- phase 2 shifts boundary *columns* (of the already row-padded tile) along
  "col" — which carries the corner cells with it, so 8-direction connectivity
  needs only 4 ppermutes, not 8.

Wrap-around is the mesh-level torus: the cyclic permutation connects the last
mesh row/col back to the first, giving globally toroidal boundaries (the
intended semantics; the reference clips at edges — ``package.scala:24-25``).

A halo of width k buys k local steps per exchange (trading ~2k redundant
boundary rows of compute for k× fewer ICI round-trips) — the same
communication-avoiding idea as blockwise/ring attention's neighbor passing.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule
from akka_game_of_life_tpu.ops.stencil import step_padded
from akka_game_of_life_tpu.parallel.mesh import (
    COL_AXIS,
    GRID_SPEC,
    ROW_AXIS,
    grid_sharding,
)


def ring_shift(x: jax.Array, axis_name: str, direction: int) -> jax.Array:
    """Cyclically send ``x`` to the next device along ``axis_name``.

    direction=+1 sends to the higher-indexed neighbor (so each device
    *receives* from the lower-indexed one), and vice versa.  Must be called
    inside ``shard_map``; shared by the dense 2-D halo exchange here and the
    packed row-ring exchange (:mod:`..parallel.packed_halo`).
    """
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + direction) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def exchange_halo(tile: jax.Array, width: int = 1) -> jax.Array:
    """Pad a local (h, w) tile to (h+2k, w+2k) with neighbor data.

    Must be called inside ``shard_map`` over a ("row", "col") mesh.
    """
    k = width
    # Phase 1 — rows. My top halo is the bottom k rows of the tile above me.
    top = ring_shift(tile[-k:, :], ROW_AXIS, +1)
    bottom = ring_shift(tile[:k, :], ROW_AXIS, -1)
    padded = jnp.concatenate([top, tile, bottom], axis=0)
    # Phase 2 — columns of the row-padded tile: corners ride along.
    left = ring_shift(padded[:, -k:], COL_AXIS, +1)
    right = ring_shift(padded[:, :k], COL_AXIS, -1)
    return jnp.concatenate([left, padded, right], axis=1)


def _local_steps(tile: jax.Array, rule: Rule, k: int) -> jax.Array:
    """k CA steps on a (k·R)-halo-padded tile, shrinking the halo by the
    rule's radius R per step (R=1 for every kind except ltl).

    (h+2kR, w+2kR) → (h, w).  The loop is unrolled (k is static and small);
    each iteration's valid region is exactly what the next needs.
    """
    for _ in range(k):
        tile = step_padded(tile, rule)
    return tile


def sharded_step_fn(
    mesh: Mesh,
    rule,
    *,
    steps_per_call: int = 1,
    halo_width: int = 1,
) -> Callable[[jax.Array], jax.Array]:
    """A jitted global-board step function over the mesh.

    Advances ``steps_per_call`` generations per invocation, exchanging a
    ``halo_width``-deep halo every ``halo_width`` steps, entirely on-device:
    the scan keeps all ICI traffic and compute inside one XLA program with no
    host round-trips (unlike the reference's wall-clock tick fan-out,
    ``BoardCreator.scala:107,113-116``).
    """
    rule = resolve_rule(rule)
    if steps_per_call % halo_width:
        raise ValueError(
            f"steps_per_call={steps_per_call} must be a multiple of "
            f"halo_width={halo_width}"
        )
    n_exchanges = steps_per_call // halo_width
    # halo_width counts STEPS per exchange; the exchanged pad is deeper for
    # radius-R rules (each step consumes R halo cells per side).
    pad = halo_width * rule.radius

    def local(tile: jax.Array) -> jax.Array:
        def body(t, _):
            return _local_steps(exchange_halo(t, pad), rule, halo_width), None

        out, _ = jax.lax.scan(body, tile, None, length=n_exchanges)
        return out

    mapped = jax.shard_map(local, mesh=mesh, in_specs=GRID_SPEC, out_specs=GRID_SPEC)
    sharding = grid_sharding(mesh)

    @functools.wraps(mapped)
    def stepped(board: jax.Array) -> jax.Array:
        # Trace-time guard: without it, exchange_halo's tile[-pad:] would
        # silently clamp on undersized tiles and ship a wrong halo
        # (surfacing later as a cryptic scan carry-shape mismatch).
        validate_tile_shape(mesh, board.shape, halo_width, rule.radius)
        return mapped(board)

    return jax.jit(stepped, in_shardings=sharding, out_shardings=sharding)


def exchange_bytes(
    mesh_shape, tile_shape, pad: int, itemsize: int = 1
) -> int:
    """Analytic bytes ONE width-``pad`` halo exchange moves across the whole
    mesh — the data-movement cost model behind the ``gol_halo_bytes_total``
    metric (Casper's observation: halo traffic, not flops, prices a
    distributed stencil).

    Mirrors :func:`exchange_halo`'s two phases per device: ``2·pad`` boundary
    rows of the (h, w) tile along the row axis, then ``2·pad`` boundary
    columns of the row-padded ``(h+2·pad, w)`` tile along the column axis
    (corners ride with phase 2).  A 1-long mesh axis moves nothing — the
    ppermute is self-to-self.  ``itemsize`` prices the element (1 for dense
    uint8 boards, 4 for packed uint32 word columns)."""
    mr, mc = mesh_shape
    h, w = tile_shape
    per_tile = 0
    if mr > 1:
        per_tile += 2 * pad * w
    if mc > 1:
        per_tile += 2 * pad * (h + 2 * pad)
    return mr * mc * per_tile * itemsize


def validate_tile_shape(
    mesh: Mesh, board_shape, halo_width: int, radius: int = 1
) -> None:
    """Halo exchange needs tiles at least as tall/wide as the exchanged pad
    (``halo_width`` steps × the rule's radius in cells per side)."""
    pad = halo_width * radius
    h = board_shape[-2] // mesh.shape[ROW_AXIS]
    w = board_shape[-1] // mesh.shape[COL_AXIS]
    if h < pad or w < pad:
        raise ValueError(
            f"tile {(h, w)} smaller than the {pad}-cell halo "
            f"({halo_width} steps x radius {radius}); "
            f"use a smaller mesh or halo"
        )
