"""Pallas TPU kernel for the bit-packed Life stencil.

The XLA bitpack path (:mod:`akka_game_of_life_tpu.ops.bitpack`) materializes
its row/word rolls and triple-sum planes in HBM between fused passes; here the
whole step — halo assembly, horizontal word shifts, carry-save row sums, rule
table — runs over one VMEM-resident row block, so HBM sees exactly one read
and one write of the packed grid per sweep.  On top of that the kernel is
*temporally blocked*: each grid step loads ``block_rows + 2k`` packed rows and
advances its central ``block_rows`` by ``k`` generations in VMEM before
writing back, cutting HBM traffic a further ~k× (the same
communication-avoiding trade the sharded halo path makes across chips — see
``parallel/packed_halo.py`` — applied chip-internally to the HBM↔VMEM
boundary).

The torus wraps through the BlockSpec ``index_map`` modulo: the north/south
halo blocks of row-block *i* are separate views of the same packed array at
block indices ``(i*B/k ± …) % (H/k)``, so no host-side padding or roll ever
exists.  Grid iterations on TPU run sequentially per core; blocks are
pipelined HBM→VMEM by Mosaic's double buffering.

Reference capability note: this kernel is the end point of collapsing the
reference's per-cell actor protocol (`CellActor.scala:63-89`,
`NextStateCellGathererActor.scala:32-45` — ~20 actor messages per cell per
epoch) into pure on-chip arithmetic: 32 cells per uint32 lane, ~1.2 VPU bit-ops
per cell per generation, zero messages.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from akka_game_of_life_tpu.ops.bitpack import (
    LANE_BITS,
    step_padded_rows,
    require_packed_support,
)
from akka_game_of_life_tpu.ops.rules import resolve_rule

DEFAULT_BLOCK_ROWS = 256
DEFAULT_STEPS_PER_SWEEP = 8
DEFAULT_BLOCK_ROWS_CAP = 128  # fallback cap when no measured band applies

# Measured-best VMEM row blocks by board height, from on-device `tune`
# sweeps (the autotuner, runtime/autotune.py; raw logs in artifacts/ and
# BASELINE.md).  auto_block_rows consults the nearest band so auto-sizing
# tracks measurements instead of one hardcoded constant; unmeasured heights
# fall back to the nearest measured band's cap (scheduling behavior changes
# slowly with size and every cap is still validated for divisibility).
MEASURED_BLOCK_ROWS_CAPS = {
    65536: 128,  # round-3 manual sweep + round-4 tune: b=128/k=8 optimum
}


def _round_up8(n: int) -> int:
    return -(-n // 8) * 8


def measured_cap(height: int) -> int:
    """The block-rows cap for ``height``: the measured band nearest in log
    scale, or DEFAULT_BLOCK_ROWS_CAP if the table is somehow empty."""
    if not MEASURED_BLOCK_ROWS_CAPS:
        return DEFAULT_BLOCK_ROWS_CAP
    import math

    band = min(
        MEASURED_BLOCK_ROWS_CAPS,
        key=lambda h: abs(math.log2(max(height, 1)) - math.log2(h)),
    )
    return MEASURED_BLOCK_ROWS_CAPS[band]


def auto_block_rows(height: int, cap: Optional[int] = None) -> Optional[int]:
    """The VMEM row block auto-sizing rule, shared by the product runtime
    and the bench suite: the largest 8-multiple divisor of ``height`` up to
    ``cap`` (default: the measured cap for this height band — see
    MEASURED_BLOCK_ROWS_CAPS), or None if the height has no 8-multiple
    divisor."""
    if cap is None:
        cap = measured_cap(height)
    for b in range(cap, 7, -8):
        if height % b == 0:
            return b
    return None


def auto_steps_per_sweep(
    n_steps: int, block_rows: int, cap: int = DEFAULT_STEPS_PER_SWEEP
) -> int:
    """The largest feasible sweep depth <= ``cap`` that divides ``n_steps``
    with sublane-aligned halo blocks.  The single feasibility rule lives
    here; the sharded path (``parallel/pallas_halo.plan_exchange``) calls
    this with its halo-depth cap rather than re-deriving the alignment."""
    candidates = [
        d
        for d in range(1, cap + 1)
        if n_steps % d == 0 and block_rows % _round_up8(d) == 0
    ]
    if not candidates:
        raise ValueError(
            f"no feasible steps_per_sweep for n_steps={n_steps}, "
            f"block_rows={block_rows} (block_rows must be a positive "
            f"multiple of 8)"
        )
    return max(candidates)


def temporal_sweep_planes_fn(
    step_planes_fn: Callable[[list], list],
    *,
    n_planes: int,
    block_rows: int,
    steps_per_sweep: int,
    interpret: bool,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[tuple], tuple]:
    """THE temporally-blocked Pallas sweep: ``n_planes`` separate 2-D
    arrays (each (rows, packed words)) advancing in lockstep.  The binary
    board is the 1-plane case (:func:`packed_sweep_fn`); Generations /
    WireWorld plane stacks pass one operand per plane.

    Mosaic requires sublane-dim block sizes divisible by 8, so the halo
    blocks are ``hb = round_up(k, 8)`` rows; the kernel statically slices
    the ``k`` rows actually adjacent to the center block (the last k of
    the north block, the first k of the south block).  The torus wraps
    through the halo BlockSpec ``index_map`` modulo.

    Why separate 2-D operands and not one (m, rows, words) stack with a
    carried leading axis?  That shape hands Mosaic 3-D VMEM blocks with a
    tiny leading dim, and on hardware the stacked Generations sweep
    measured *slower* than the XLA plane scan (2.81 vs 3.19×10¹⁰ at 8192²
    — VERDICT.md round-3 weak #5) while the binary kernel's clean 2-D
    blocks ran at 1.82×10¹².  Per-plane operands give every block the
    same 2-D (rows, words) tiling as the binary kernel; the plane-wise
    compute inside the kernel is unchanged.

    ``vmem_limit_bytes`` raises Mosaic's scoped-VMEM budget past its 16 MB
    default — required for large blocks (e.g. block_rows=256 at 65536²
    wants ~20.5 MB of double-buffered blocks + scratch).
    """
    b, k = block_rows, steps_per_sweep
    if k < 1:
        raise ValueError(f"steps_per_sweep={k} must be >= 1")
    hb = _round_up8(k)
    if b % hb:
        raise ValueError(
            f"block_rows={b} must be a multiple of {hb} "
            f"(steps_per_sweep={k} rounded up to the 8-row sublane tile)"
        )
    m = n_planes

    def kernel(*refs):
        ins, outs = refs[: 3 * m], refs[3 * m :]
        exts = [
            jnp.concatenate(
                [
                    ins[3 * j][hb - k :],
                    ins[3 * j + 1][...],
                    ins[3 * j + 2][:k],
                ],
                axis=0,
            )
            for j in range(m)
        ]
        for _ in range(k):
            exts = step_planes_fn(exts)
        for j in range(m):
            outs[j][...] = exts[j]

    def sweep(planes: tuple) -> tuple:
        if len(planes) != m:
            raise ValueError(f"expected {m} planes, got {len(planes)}")
        if any(
            p.shape != planes[0].shape or p.dtype != planes[0].dtype
            for p in planes[1:]
        ):
            raise ValueError(
                f"planes must share shape/dtype, got "
                f"{[(p.shape, str(p.dtype)) for p in planes]}"
            )
        h, words = planes[0].shape
        if h % b:
            raise ValueError(f"grid height {h} not a multiple of block_rows={b}")
        n_row_blocks = h // b
        halo_blocks = h // hb

        def specs():
            # One (north, center, south) triple per plane — identical
            # index maps to the single-array sweep, all 2-D blocks.
            return [
                pl.BlockSpec(
                    (hb, words),
                    lambda i: ((i * (b // hb) - 1) % halo_blocks, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec((b, words), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec(
                    (hb, words),
                    lambda i: (((i + 1) * (b // hb)) % halo_blocks, 0),
                    memory_space=pltpu.VMEM,
                ),
            ]

        grid_spec = pl.GridSpec(
            grid=(n_row_blocks,),
            in_specs=[s for _ in range(m) for s in specs()],
            out_specs=[
                pl.BlockSpec((b, words), lambda i: (i, 0), memory_space=pltpu.VMEM)
                for _ in range(m)
            ],
        )
        compiler_params = None
        if vmem_limit_bytes is not None and not interpret:
            compiler_params = pltpu.CompilerParams(
                vmem_limit_bytes=vmem_limit_bytes
            )
        out = pl.pallas_call(
            kernel,
            out_shape=[
                jax.ShapeDtypeStruct((h, words), p.dtype) for p in planes
            ],
            grid_spec=grid_spec,
            interpret=interpret,
            compiler_params=compiler_params,
        )(*[x for p in planes for x in (p, p, p)])
        return tuple(out)

    return sweep


def packed_sweep_fn(
    rule,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: int = DEFAULT_STEPS_PER_SWEEP,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """One Pallas sweep advancing a packed (H, W/32) uint32 torus by
    ``steps_per_sweep`` generations.

    Requires ``H % block_rows == 0`` and sublane-aligned halos (see
    :func:`temporal_sweep_planes_fn` — this is its 1-plane case).
    """
    rule = resolve_rule(rule)
    require_packed_support(rule)
    inner = temporal_sweep_planes_fn(
        lambda exts: [step_padded_rows(exts[0], rule)],
        n_planes=1,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    def sweep(x: jax.Array) -> jax.Array:
        return inner((x,))[0]

    return sweep


@functools.lru_cache(maxsize=None)
def packed_multi_step_fn(
    rule_key,
    n_steps: int,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: Optional[int] = None,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Jitted n-step advance built from temporally-blocked Pallas sweeps.

    ``n_steps`` must be a multiple of the chosen ``steps_per_sweep`` (which
    defaults to the largest divisor of ``n_steps`` that is <=
    ``DEFAULT_STEPS_PER_SWEEP`` and divides ``block_rows``).
    """
    rule = resolve_rule(rule_key)
    if steps_per_sweep is None:
        steps_per_sweep = auto_steps_per_sweep(n_steps, block_rows)
    if n_steps % steps_per_sweep:
        raise ValueError(
            f"n_steps={n_steps} not a multiple of steps_per_sweep={steps_per_sweep}"
        )
    sweep = packed_sweep_fn(
        rule,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    @jax.jit
    def run(x: jax.Array) -> jax.Array:
        def body(s, _):
            return sweep(s), None

        out, _ = jax.lax.scan(body, x, None, length=n_steps // steps_per_sweep)
        return out

    from akka_game_of_life_tpu.obs.programs import registered_jit

    return registered_jit(
        "pallas", ("packed_multi_step", rule.name, n_steps, block_rows), run,
        # Packed words: 32 cells/element; the temporal blocking re-reads
        # each block once per sweep, not per step.
        cost=lambda x: {
            "cells": float(x.size) * x.dtype.itemsize * 8 * n_steps,
            "bytes": 2.0 * x.size * x.dtype.itemsize
            * (n_steps // steps_per_sweep),
            "flops": 2.0 * x.size * x.dtype.itemsize * 8 * n_steps,
        },
    )
