"""Pallas TPU kernel for the bit-packed Life stencil.

The XLA bitpack path (:mod:`akka_game_of_life_tpu.ops.bitpack`) materializes
its row/word rolls and triple-sum planes in HBM between fused passes; here the
whole step — halo assembly, horizontal word shifts, carry-save row sums, rule
table — runs over one VMEM-resident row block, so HBM sees exactly one read
and one write of the packed grid per sweep.  On top of that the kernel is
*temporally blocked*: each grid step loads ``block_rows + 2k`` packed rows and
advances its central ``block_rows`` by ``k`` generations in VMEM before
writing back, cutting HBM traffic a further ~k× (the same
communication-avoiding trade the sharded halo path makes across chips — see
``parallel/packed_halo.py`` — applied chip-internally to the HBM↔VMEM
boundary).

The torus wraps through the BlockSpec ``index_map`` modulo: the north/south
halo blocks of row-block *i* are separate views of the same packed array at
block indices ``(i*B/k ± …) % (H/k)``, so no host-side padding or roll ever
exists.  Grid iterations on TPU run sequentially per core; blocks are
pipelined HBM→VMEM by Mosaic's double buffering.

Reference capability note: this kernel is the end point of collapsing the
reference's per-cell actor protocol (`CellActor.scala:63-89`,
`NextStateCellGathererActor.scala:32-45` — ~20 actor messages per cell per
epoch) into pure on-chip arithmetic: 32 cells per uint32 lane, ~1.2 VPU bit-ops
per cell per generation, zero messages.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from akka_game_of_life_tpu.ops.bitpack import (
    LANE_BITS,
    step_padded_rows,
    require_packed_support,
)
from akka_game_of_life_tpu.ops.rules import resolve_rule

DEFAULT_BLOCK_ROWS = 256
DEFAULT_STEPS_PER_SWEEP = 8
DEFAULT_BLOCK_ROWS_CAP = 128  # fallback cap when no measured band applies

# Measured-best VMEM row blocks by board height, from on-device `tune`
# sweeps (the autotuner, runtime/autotune.py; raw logs in artifacts/ and
# BASELINE.md).  auto_block_rows consults the nearest band so auto-sizing
# tracks measurements instead of one hardcoded constant; unmeasured heights
# fall back to the nearest measured band's cap (scheduling behavior changes
# slowly with size and every cap is still validated for divisibility).
MEASURED_BLOCK_ROWS_CAPS = {
    65536: 128,  # round-3 manual sweep + round-4 tune: b=128/k=8 optimum
}


def _round_up8(n: int) -> int:
    return -(-n // 8) * 8


def measured_cap(height: int) -> int:
    """The block-rows cap for ``height``: the measured band nearest in log
    scale, or DEFAULT_BLOCK_ROWS_CAP if the table is somehow empty."""
    if not MEASURED_BLOCK_ROWS_CAPS:
        return DEFAULT_BLOCK_ROWS_CAP
    import math

    band = min(
        MEASURED_BLOCK_ROWS_CAPS,
        key=lambda h: abs(math.log2(max(height, 1)) - math.log2(h)),
    )
    return MEASURED_BLOCK_ROWS_CAPS[band]


def auto_block_rows(height: int, cap: Optional[int] = None) -> Optional[int]:
    """The VMEM row block auto-sizing rule, shared by the product runtime
    and the bench suite: the largest 8-multiple divisor of ``height`` up to
    ``cap`` (default: the measured cap for this height band — see
    MEASURED_BLOCK_ROWS_CAPS), or None if the height has no 8-multiple
    divisor."""
    if cap is None:
        cap = measured_cap(height)
    for b in range(cap, 7, -8):
        if height % b == 0:
            return b
    return None


def auto_steps_per_sweep(
    n_steps: int, block_rows: int, cap: int = DEFAULT_STEPS_PER_SWEEP
) -> int:
    """The largest feasible sweep depth <= ``cap`` that divides ``n_steps``
    with sublane-aligned halo blocks.  The single feasibility rule lives
    here; the sharded path (``parallel/pallas_halo.plan_exchange``) calls
    this with its halo-depth cap rather than re-deriving the alignment."""
    candidates = [
        d
        for d in range(1, cap + 1)
        if n_steps % d == 0 and block_rows % _round_up8(d) == 0
    ]
    if not candidates:
        raise ValueError(
            f"no feasible steps_per_sweep for n_steps={n_steps}, "
            f"block_rows={block_rows} (block_rows must be a positive "
            f"multiple of 8)"
        )
    return max(candidates)


def temporal_sweep_fn(
    step_padded_rows_fn: Callable[[jax.Array], jax.Array],
    *,
    n_prefix: int,
    block_rows: int,
    steps_per_sweep: int,
    interpret: bool,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """The shared temporally-blocked Pallas sweep over a row-tiled array
    whose LAST TWO axes are (rows, packed words), with ``n_prefix`` leading
    axes carried whole in every block (0 for the binary board, 1 for the
    Generations plane stack).

    Mosaic requires sublane-dim block sizes divisible by 8, so the halo
    blocks are ``hb = round_up(k, 8)`` rows; the kernel statically slices
    the ``k`` rows actually adjacent to the center block (the last k of the
    north block, the first k of the south block).  The torus wraps through
    the halo BlockSpec ``index_map`` modulo.

    ``vmem_limit_bytes`` raises Mosaic's scoped-VMEM budget past its 16 MB
    default — required for large blocks (e.g. block_rows=256 at 65536²
    wants ~20.5 MB of double-buffered blocks + scratch).
    """
    b, k = block_rows, steps_per_sweep
    if k < 1:
        raise ValueError(f"steps_per_sweep={k} must be >= 1")
    hb = _round_up8(k)  # Mosaic sublane alignment for the halo blocks
    if b % hb:
        raise ValueError(
            f"block_rows={b} must be a multiple of {hb} "
            f"(steps_per_sweep={k} rounded up to the 8-row sublane tile)"
        )
    row_ax = n_prefix
    pre = (slice(None),) * n_prefix

    def kernel(north_ref, center_ref, south_ref, out_ref):
        ext = jnp.concatenate(
            [
                north_ref[pre + (slice(hb - k, None),)],
                center_ref[...],
                south_ref[pre + (slice(None, k),)],
            ],
            axis=row_ax,
        )  # (..., B + 2k, W)
        for _ in range(k):
            ext = step_padded_rows_fn(ext)
        out_ref[...] = ext

    def sweep(x: jax.Array) -> jax.Array:
        prefix = x.shape[:n_prefix]
        h, words = x.shape[row_ax], x.shape[row_ax + 1]
        if h % b:
            raise ValueError(f"grid height {h} not a multiple of block_rows={b}")
        # h % b == 0 and b % hb == 0 together imply h % hb == 0, so the
        # hb-row halo views below always tile the array exactly.
        n_row_blocks = h // b
        halo_blocks = h // hb  # the same array viewed in (hb, words) blocks
        zeros = (0,) * n_prefix

        grid_spec = pl.GridSpec(
            grid=(n_row_blocks,),
            in_specs=[
                # North halo: the hb-row block ending exactly where the center
                # block starts (its last k rows are the true halo).
                pl.BlockSpec(
                    prefix + (hb, words),
                    lambda i: zeros + ((i * (b // hb) - 1) % halo_blocks, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    prefix + (b, words),
                    lambda i: zeros + (i, 0),
                    memory_space=pltpu.VMEM,
                ),
                # South halo: the hb-row block starting just below the center
                # block (its first k rows are the true halo).
                pl.BlockSpec(
                    prefix + (hb, words),
                    lambda i: zeros + (((i + 1) * (b // hb)) % halo_blocks, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                prefix + (b, words),
                lambda i: zeros + (i, 0),
                memory_space=pltpu.VMEM,
            ),
        )
        compiler_params = None
        if vmem_limit_bytes is not None and not interpret:
            compiler_params = pltpu.CompilerParams(
                vmem_limit_bytes=vmem_limit_bytes
            )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid_spec=grid_spec,
            interpret=interpret,
            compiler_params=compiler_params,
        )(x, x, x)

    return sweep


def packed_sweep_fn(
    rule,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: int = DEFAULT_STEPS_PER_SWEEP,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """One Pallas sweep advancing a packed (H, W/32) uint32 torus by
    ``steps_per_sweep`` generations.

    Requires ``H % block_rows == 0`` and sublane-aligned halos (see
    :func:`temporal_sweep_fn`).
    """
    rule = resolve_rule(rule)
    require_packed_support(rule)
    return temporal_sweep_fn(
        lambda ext: step_padded_rows(ext, rule),
        n_prefix=0,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )


@functools.lru_cache(maxsize=None)
def packed_multi_step_fn(
    rule_key,
    n_steps: int,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: Optional[int] = None,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Jitted n-step advance built from temporally-blocked Pallas sweeps.

    ``n_steps`` must be a multiple of the chosen ``steps_per_sweep`` (which
    defaults to the largest divisor of ``n_steps`` that is <=
    ``DEFAULT_STEPS_PER_SWEEP`` and divides ``block_rows``).
    """
    rule = resolve_rule(rule_key)
    if steps_per_sweep is None:
        steps_per_sweep = auto_steps_per_sweep(n_steps, block_rows)
    if n_steps % steps_per_sweep:
        raise ValueError(
            f"n_steps={n_steps} not a multiple of steps_per_sweep={steps_per_sweep}"
        )
    sweep = packed_sweep_fn(
        rule,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    @jax.jit
    def run(x: jax.Array) -> jax.Array:
        def body(s, _):
            return sweep(s), None

        out, _ = jax.lax.scan(body, x, None, length=n_steps // steps_per_sweep)
        return out

    return run
