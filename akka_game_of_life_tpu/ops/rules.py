"""Cellular-automaton rules as *data*.

The reference hard-codes its (buggy) transition rule in actor code
(``NextStateCellGathererActor.scala:44`` — a live cell dies iff it has exactly
3 live neighbors, nothing is ever born).  Here the rule is a value: a pair of
neighbor-count bitmasks (birth / survive) plus a state count, which covers

- Conway B3/S23 and every outer-totalistic "life-like" rule on the Moore
  neighborhood (HighLife B36/S23, Day & Night B3678/S34678, Seeds B2/S, ...);
- multi-state *Generations* CA (Brian's Brain ``/2/3``, Star Wars ``345/2/4``)
  where dead-ing cells decay through refractory states.

Keeping the rule as two small integers lets every kernel (dense roll-based,
halo-sharded, bit-packed Pallas) close over it as a compile-time constant so
XLA folds the thresholding into the stencil fusion.
"""

from __future__ import annotations

import dataclasses
import re
from typing import FrozenSet, Optional

import numpy as np

_MAX_NEIGHBORS = 8  # Moore neighborhood


@dataclasses.dataclass(frozen=True)
class Rule:
    """An outer-totalistic CA rule on the Moore-8 neighborhood.

    ``birth``/``survive`` are the neighbor counts (0..8) at which a dead cell
    becomes alive / a live cell stays alive.  ``states`` is the total number of
    cell states: 2 for plain life-like rules; >2 for Generations rules, where a
    live cell that fails to survive enters state 2 and decays 2 → 3 → ... →
    states-1 → 0 (dead), and decaying cells count as *not alive* for neighbor
    totals but occupy the cell (no birth there).
    """

    birth: FrozenSet[int]
    survive: FrozenSet[int]
    states: int = 2
    # Cosmetic only: excluded from __eq__/__hash__ so semantically identical
    # rules share one jit-compilation cache entry in step_fn/multi_step_fn.
    name: Optional[str] = dataclasses.field(default=None, compare=False)
    # Rule family.  "totalistic" covers life-like + Generations via the
    # birth/survive masks above; "wireworld" reuses the same machinery with
    # shifted meanings: state 1 = electron head (the counted state), 2 =
    # tail, 3 = conductor; ``birth`` holds the head-neighbor counts ({1, 2})
    # at which a CONDUCTOR excites to a head; heads always become tails,
    # tails conductors, empty stays empty.  "ltl" is Larger than Life:
    # the same outer-totalistic birth/survive semantics on a radius-R
    # Moore neighborhood ((2R+1)² - 1 neighbors) — counts come from
    # separable shift-add window sums instead of the Moore-8 adder
    # network (ops/ltl.py).  Every kernel's neighbor-count pipeline
    # (alive = state == 1) is shared; only the transition/count-geometry
    # differs per kind.
    kind: str = "totalistic"
    radius: int = 1  # neighborhood radius; >1 only for kind="ltl"
    # Neighborhood norm for kind="ltl": "box" = radius-R Moore (Golly NM),
    # "diamond" = von Neumann L1 ball (Golly NN).  Radius-1 families always
    # use the Moore box.
    neighborhood: str = "box"

    def __post_init__(self) -> None:
        if self.kind not in ("totalistic", "wireworld", "ltl"):
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if self.kind == "wireworld" and self.states != 4:
            raise ValueError("wireworld has exactly 4 states")
        if self.kind != "ltl" and self.radius != 1:
            raise ValueError(f"radius {self.radius} requires kind='ltl'")
        if self.neighborhood not in ("box", "diamond"):
            raise ValueError(f"unknown neighborhood {self.neighborhood!r}")
        if self.neighborhood != "box" and self.kind != "ltl":
            raise ValueError("neighborhood='diamond' requires kind='ltl'")
        if self.kind == "ltl":
            if not (1 <= self.radius <= 10):
                raise ValueError(f"ltl radius must be in 1..10, got {self.radius}")
            if self.states != 2:
                raise ValueError("ltl rules are binary")
        if not (2 <= self.states <= 255):
            # State arrays are uint8 (ops.stencil.STATE_DTYPE).
            raise ValueError(f"states must be in 2..255, got {self.states}")
        max_n = self.max_neighbors
        for s in self.birth | self.survive:
            if not (0 <= s <= max_n):
                raise ValueError(
                    f"neighbor count out of range 0..{max_n}: {s}"
                )

    @property
    def birth_mask(self) -> int:
        """Bit i set iff a dead cell with i live neighbors is born."""
        m = 0
        for b in self.birth:
            m |= 1 << b
        return m

    @property
    def survive_mask(self) -> int:
        """Bit i set iff a live cell with i live neighbors survives."""
        m = 0
        for s in self.survive:
            m |= 1 << s
        return m

    @property
    def is_binary(self) -> bool:
        return self.states == 2

    @property
    def is_totalistic(self) -> bool:
        return self.kind == "totalistic"

    @property
    def is_linear(self) -> bool:
        """True iff this rule's global update is XOR-linear over GF(2) —
        the odd-rule family :func:`linear_kernel` proves membership of.
        Linear rules are the ones ``ops/fastforward.py`` can jump T epochs
        in O(log T) device programs instead of O(T)."""
        return linear_kernel(self) is not None

    @property
    def max_neighbors(self) -> int:
        """Largest possible neighbor count: (2R+1)² - 1 for the Moore box,
        2R(R+1) for the von Neumann diamond (L1 ball minus center)."""
        if self.neighborhood == "diamond":
            return 2 * self.radius * (self.radius + 1)
        return (2 * self.radius + 1) ** 2 - 1

    def rulestring(self) -> str:
        if self.kind == "ltl":
            # Range notation, round-trippable through parse_rule:
            # "R5,B34-45,S33-57" (counts exclude the center cell);
            # diamond neighborhoods append ",NN" (Golly's von Neumann tag).
            nn = ",NN" if self.neighborhood == "diamond" else ""
            return (
                f"R{self.radius},B{_ranges(self.birth)},S{_ranges(self.survive)}{nn}"
            )
        if not self.is_totalistic:
            # Non-totalistic families have no B/S encoding; the registered
            # name is the canonical round-trippable spelling (checkpoint
            # metadata resolves it back through NAMED_RULES).
            return self.name or self.kind
        b = "".join(str(i) for i in sorted(self.birth))
        s = "".join(str(i) for i in sorted(self.survive))
        if self.is_binary:
            return f"B{b}/S{s}"
        return f"{s}/{b}/{self.states}"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.name or self.rulestring()


def _ranges(counts: FrozenSet[int]) -> str:
    """Collapse a count set to comma-separated values/ranges: {3,4,5,9} →
    "3-5,9"."""
    out = []
    run = []
    for v in sorted(counts):
        if run and v == run[-1] + 1:
            run.append(v)
        else:
            if run:
                out.append(run)
            run = [v]
    if run:
        out.append(run)
    return ",".join(
        f"{r[0]}-{r[-1]}" if len(r) > 1 else str(r[0]) for r in out
    )


def _parse_ranges(spec: str) -> FrozenSet[int]:
    vals = set()
    for part in spec.split(","):
        if not part:
            continue
        try:
            if "-" in part:
                lo_s, hi_s = part.split("-")
                lo, hi = int(lo_s), int(hi_s)
                if lo > hi:
                    raise ValueError(f"descending range {part!r}")
                vals.update(range(lo, hi + 1))
            else:
                vals.add(int(part))
        except ValueError as e:
            raise ValueError(
                f"bad count spec {part!r} in {spec!r}: {e}"
            ) from None
    return frozenset(vals)


_LTL_RE = re.compile(
    r"^R(?P<r>\d+),B(?P<b>[\d,\-]*),S(?P<s>[\d,\-]*)(?:,N(?P<n>[NM]))?$",
    re.IGNORECASE
)
_BS_RE = re.compile(r"^B(?P<b>\d*)/S(?P<s>\d*)$", re.IGNORECASE)
_SB_RE = re.compile(r"^(?P<s>\d*)/(?P<b>\d*)$")
_GEN_RE = re.compile(r"^(?P<s>\d*)/(?P<b>\d*)/(?P<c>\d+)$")
_BSG_RE = re.compile(r"^B(?P<b>\d*)/S(?P<s>\d*)/(?:C|G)?(?P<c>\d+)$", re.IGNORECASE)


def _digits(ds: str) -> FrozenSet[int]:
    return frozenset(int(ch) for ch in ds)


def parse_rule(rulestring: str, name: Optional[str] = None) -> Rule:
    """Parse a rulestring into a :class:`Rule`.

    Accepted formats (all standard in the CA literature):

    - ``"B3/S23"``        — birth/survival (Golly canonical)
    - ``"23/3"``          — survival/birth (older S/B convention)
    - ``"345/2/4"``       — Generations: survival/birth/states
    - ``"B2/S/3"``, ``"B2/S/C3"`` — Generations, B/S-first variant
    """
    s = rulestring.strip().replace(" ", "")
    m = _LTL_RE.match(s)
    if m:
        return Rule(
            birth=_parse_ranges(m.group("b")),
            survive=_parse_ranges(m.group("s")),
            radius=int(m.group("r")),
            kind="ltl",
            # Golly tags: NM = Moore box (the default), NN = von Neumann.
            neighborhood="diamond" if (m.group("n") or "M").upper() == "N" else "box",
            name=name,
        )
    for rx, has_states in ((_BSG_RE, True), (_GEN_RE, True), (_BS_RE, False), (_SB_RE, False)):
        m = rx.match(s)
        if m:
            states = int(m.group("c")) if has_states else 2
            return Rule(
                birth=_digits(m.group("b")),
                survive=_digits(m.group("s")),
                states=states,
                name=name,
            )
    raise ValueError(f"unrecognized rulestring: {rulestring!r}")


def linear_kernel(spec) -> Optional[np.ndarray]:
    """The GF(2) one-step kernel of an XOR-linear rule, or ``None``.

    A rule is XOR-linear ("odd rule", Odd-Rule Cellular Automata on the
    Square Grid / the Linear Acceleration Theorem, PAPERS.md) iff its next
    state is the XOR of a fixed cell subset of the neighborhood — then T
    steps compose into ONE convolution by the kernel's T-th XOR-power
    (``ops/fastforward.py``).  This predicate is a *proof by case
    analysis*, not a heuristic: an outer-totalistic binary rule treats all
    neighbors symmetrically, so the only GF(2)-linear members are

    - ``birth = odd counts, survive = odd counts``  → next = parity of the
      neighborhood (center excluded) — the replicator family;
    - ``birth = odd counts, survive = even counts`` → next = center XOR
      neighborhood parity — the Fredkin family;
    - ``birth = ∅, survive = all counts``           → the identity map;
    - ``birth = ∅, survive = ∅``                    → the zero map.

    Everything else (Conway, HighLife, Seeds, every Generations/wireworld
    rule, every non-parity LtL band) is provably non-linear and returns
    ``None`` — it must never be fast-forwarded.  The returned kernel is a
    centered ``(2R+1, 2R+1)`` uint8 0/1 plane (box or diamond support,
    center set for the Fredkin/identity cases)."""
    rule = resolve_rule(spec)
    if rule.states != 2 or rule.kind not in ("totalistic", "ltl"):
        return None  # Generations decay / wireworld phases are affine-free
    m = rule.max_neighbors
    odd = frozenset(range(1, m + 1, 2))
    even = frozenset(range(0, m + 1, 2))
    r = rule.radius
    side = 2 * r + 1
    nbhd = np.zeros((side, side), dtype=np.uint8)
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            if (dy, dx) == (0, 0):
                continue
            if rule.neighborhood == "diamond" and abs(dy) + abs(dx) > r:
                continue
            nbhd[dy + r, dx + r] = 1
    if rule.birth == odd and rule.survive == odd:
        return nbhd  # pure neighborhood parity (replicator family)
    if rule.birth == odd and rule.survive == even:
        nbhd[r, r] = 1  # center XOR parity (Fredkin family)
        return nbhd
    if not rule.birth and rule.survive == frozenset(range(m + 1)):
        ident = np.zeros((side, side), dtype=np.uint8)
        ident[r, r] = 1
        return ident
    if not rule.birth and not rule.survive:
        return np.zeros((side, side), dtype=np.uint8)
    return None


# Named rules covering the BASELINE.json benchmark configs.
CONWAY = Rule(frozenset({3}), frozenset({2, 3}), name="conway")
HIGHLIFE = Rule(frozenset({3, 6}), frozenset({2, 3}), name="highlife")
DAY_AND_NIGHT = Rule(
    frozenset({3, 6, 7, 8}), frozenset({3, 4, 6, 7, 8}), name="day-and-night"
)
SEEDS = Rule(frozenset({2}), frozenset(), name="seeds")
LIFE_WITHOUT_DEATH = Rule(frozenset({3}), frozenset(range(9)), name="life-without-death")
BRIANS_BRAIN = Rule(frozenset({2}), frozenset(), states=3, name="brians-brain")
STAR_WARS = Rule(frozenset({2}), frozenset({3, 4, 5}), states=4, name="star-wars")
# WireWorld (Silverman 1987): 0 empty, 1 electron head, 2 tail, 3 conductor;
# a conductor becomes a head iff it has 1 or 2 head neighbors.  The classic
# non-totalistic digital-logic CA — wires, diodes, gates.
WIREWORLD = Rule(
    frozenset({1, 2}), frozenset(), states=4, name="wireworld", kind="wireworld"
)
# Bugs (Evans 1996): the canonical Larger-than-Life rule, radius-5 Moore.
# Golly's "R5,C0,M1,S34..58,B34..45,NM" counts the center for survival
# (M1); our survive set is in neighbors-excluding-center terms, hence the
# -1 shift: S34..58 with self → {33..57} without.
BUGS = Rule(
    frozenset(range(34, 46)),
    frozenset(range(33, 58)),
    radius=5,
    kind="ltl",
    name="bugs",
)
# The XOR-linear (odd-rule) catalog — the rules ops/fastforward.py can
# jump T epochs in O(log T) device programs (see linear_kernel above).
# Fredkin (B1357/S02468): next = center XOR Moore-8 parity — every pattern
# replicates into 8 copies of itself.  Replicator (B1357/S1357): pure
# neighborhood parity, center excluded.
FREDKIN = Rule(
    frozenset({1, 3, 5, 7}), frozenset({0, 2, 4, 6, 8}), name="fredkin"
)
REPLICATOR = Rule(
    frozenset({1, 3, 5, 7}), frozenset({1, 3, 5, 7}), name="replicator"
)
# The von Neumann parity rule (the classic 1-bit replicator on the L1
# diamond) and a radius-2 LtL member — witnesses that linearity detection
# covers diamond neighborhoods and radius > 1.
FREDKIN_DIAMOND = Rule(
    frozenset({1, 3}),
    frozenset({0, 2, 4}),
    radius=1,
    kind="ltl",
    neighborhood="diamond",
    name="fredkin-diamond",
)
REPLICATOR_R2 = Rule(
    frozenset(range(1, 25, 2)),
    frozenset(range(1, 25, 2)),
    radius=2,
    kind="ltl",
    name="replicator-r2",
)

# Every named linear rule (tests sweep this alongside the non-linear rest
# of NAMED_RULES; docs/OPERATIONS.md "Logarithmic fast-forward").
LINEAR_RULES = (FREDKIN, REPLICATOR, FREDKIN_DIAMOND, REPLICATOR_R2)

NAMED_RULES = {
    r.name: r
    for r in (
        CONWAY,
        HIGHLIFE,
        DAY_AND_NIGHT,
        SEEDS,
        LIFE_WITHOUT_DEATH,
        BRIANS_BRAIN,
        STAR_WARS,
        WIREWORLD,
        BUGS,
    )
    + LINEAR_RULES
}


def resolve_rule(spec) -> Rule:
    """Resolve a Rule from a Rule, a known name, or a rulestring."""
    if isinstance(spec, Rule):
        return spec
    if isinstance(spec, str):
        key = spec.strip().lower()
        if key in NAMED_RULES:
            return NAMED_RULES[key]
        return parse_rule(spec)
    raise TypeError(f"cannot resolve rule from {spec!r}")
