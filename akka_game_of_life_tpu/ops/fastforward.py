"""Logarithmic time travel: O(log T) fast-forward for XOR-linear rules.

Every stepper in this repo — dense, bit-packed, Pallas, banded-matmul,
sparse-gated — pays O(T) device programs to advance T epochs.  For the
odd-rule family (``ops/rules.linear_kernel``) the update is *linear over
GF(2)*: one step is XOR-convolution of the board by a fixed ±R kernel
("Odd-Rule Cellular Automata on the Square Grid", PAPERS.md), and step
composition is legal across the whole neighborhood (the Linear
Acceleration Theorem, PAPERS.md).  T steps therefore collapse to ONE
convolution by the kernel's T-th XOR-power — and over GF(2) that power
has special structure this module exploits twice:

- **Squaring is free (Frobenius).**  In a ring of characteristic 2,
  ``(Σ aᵢ xⁱ)² = Σ aᵢ x²ⁱ``: squaring a kernel just doubles every offset
  (mod the torus).  So ``K^(2^k)`` is the base kernel with offsets scaled
  by ``2^k`` — never more set cells than K itself.
- **The factored jump.**  ``K^T = Π K^(2^k)`` over T's set bits, and the
  factors commute, so the board is advanced by applying each scaled base
  kernel directly: ``popcount(T) ≤ log₂T + 1`` device programs of ≤ |K|
  rolls + XORs each (:func:`fast_forward`).  Epoch 2³⁰ of a 16384² board
  is ONE program of 8 rolls — O(board) work total, whatever T is.

The *materialized* composed kernel (:func:`pow_offsets` /
:func:`kernel_plane`, genuine XOR-convolution square-and-multiply on a
sparse offset set) exists for certification, analysis, and the
single-wrapped-convolution story: its support dilates as R·T per the PR 9
influence bound (:func:`support_radius`) until it wraps the torus, where
it caps at the board size — every intermediate working set is priced
through :mod:`ops/guard` *before* composition, never allocate-and-die.

For the separable linear kernels (the Fredkin family: full (2R+1)² box,
center included, = ones ⊗ ones) the T-step jump also factors into two
one-dimensional XOR-powers, so it evaluates as two blocked banded matrix
multiplies over GF(2) — the PR 11 MXU machinery with the band *pattern*
generalized from contiguous ±R to the 1-D kernel's XOR-power mask
(:func:`jump_matmul_fn`); counts accumulate exactly (int8→int32 on TPU,
f32 elsewhere) and reduce mod 2, so the MXU path rides for free.

Certification (:func:`certify_jump`) compares the digest of a jump
against the digest of the same T iterated through the ordinary stepper —
the jump-vs-iterate contract every product surface samples
(``Simulation.fast_forward``, the serve fast path, ``bench_suite``
config 16).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.obs.programs import registered_jit
from akka_game_of_life_tpu.ops import guard
from akka_game_of_life_tpu.ops.rules import linear_kernel, resolve_rule


def kernel_offsets(rule) -> np.ndarray:
    """The linear rule's one-step kernel as centered ``(k, 2)`` int64
    offsets (the sparse twin of ``linear_kernel``'s plane).  Raises
    ``ValueError`` for non-linear rules — the refusal every fast-forward
    surface routes through, so a non-linear rule can never be silently
    jumped."""
    rule = resolve_rule(rule)
    kern = linear_kernel(rule)
    if kern is None:
        raise ValueError(
            f"rule {rule} is not XOR-linear: fast-forward applies only to "
            f"the odd-rule family (birth on odd counts with odd/even "
            f"survival — see ops/rules.linear_kernel); every other rule "
            f"must iterate"
        )
    r = rule.radius
    ys, xs = np.nonzero(kern)
    return np.stack([ys.astype(np.int64) - r, xs.astype(np.int64) - r], 1)


def support_radius(rule, t: int) -> int:
    """The composed kernel's support half-width after ``t`` steps: R·t —
    the same one-cell-per-step influence bound PR 9's activity gate rests
    on, applied T times.  The torus caps it: once ``2·R·t + 1`` reaches
    the board side the kernel wraps and support saturates at board size."""
    return resolve_rule(rule).radius * int(t)


def _parity_dedup(offs: np.ndarray, shape: Tuple[int, int]) -> np.ndarray:
    """Canonicalize offsets mod the torus and cancel pairs — XOR-conv
    coefficients live in GF(2), so an offset appearing an even number of
    times vanishes."""
    h, w = shape
    if len(offs) == 0:
        return offs.reshape(0, 2)
    offs = np.stack([offs[:, 0] % h, offs[:, 1] % w], 1)
    uniq, counts = np.unique(offs, axis=0, return_counts=True)
    return uniq[counts % 2 == 1]


def _compose_guard(n_left: int, n_right: int, what: str) -> None:
    """Price one XOR-convolution's offset working set (the n_left·n_right
    candidate rows materialized before parity cancellation) up front."""
    rows = n_left * n_right
    guard.require_intermediates_fit(
        rows * 2 * 8 * 2,  # (rows, 2) int64, candidate + unique scratch
        what=what,
        detail=(
            "Use the factored jump (fast_forward) instead — it applies "
            "the per-bit scaled kernels to the board directly and never "
            "materializes the composed kernel."
        ),
        shapes=[((rows, 2), 8), ((rows, 2), 8)],
    )


# Span ceiling: every surface bounds its per-jump program count (and jit
# cache growth) by the span's bit length, so one absurd request cannot
# mint unbounded compiles.  Purely a DoS bound — offset arithmetic is
# exact at ANY span, because scale factors reduce mod the torus side
# BEFORE multiplying (``_scaled_offsets``: 2^k·o ≡ (2^k mod n)·o mod n,
# and (n−1)·radius always fits int64).  2^62 epochs is beyond any
# physical use, so the cap costs nothing.
MAX_SPAN_BITS = 62


def _scaled_offsets(base: np.ndarray, k: int, shape: Tuple[int, int]) -> np.ndarray:
    """The 2^k-Frobenius-scaled kernel offsets, canonical mod the torus
    and parity-deduped.  The scale reduces mod each side first — a raw
    int64 ``base << k`` would silently wrap for k ≥ 61, and
    (x mod 2^64) mod n ≠ x mod n on non-power-of-two sides."""
    h, w = shape
    sy, sx = pow(2, k, h), pow(2, k, w)
    return _parity_dedup(
        np.stack([base[:, 0] * sy, base[:, 1] * sx], 1), (h, w)
    )


def _require_span(t: int) -> int:
    t = int(t)
    if t < 0:
        raise ValueError(f"cannot fast-forward a negative span: t={t}")
    if t.bit_length() > MAX_SPAN_BITS:
        raise ValueError(
            f"fast-forward span t={t} exceeds {MAX_SPAN_BITS} bits "
            f"(offsets scale as 2^k in int64, and the per-jump program "
            f"count is bounded by the span's bit length)"
        )
    return t


def pow_offsets(rule, t: int, shape: Tuple[int, int]) -> np.ndarray:
    """The T-th XOR-power of the one-step kernel as sparse offsets on the
    ``(H, W)`` torus, by square-and-multiply: squaring is the Frobenius
    offset-doubling (exact, free); each multiply-by-base is a genuine
    XOR-convolution whose candidate working set is guard-priced before it
    is built.  Support is bounded by ``min(2·R·t + 1, side)`` per axis
    (:func:`support_radius`), so the offset count never exceeds the board
    — the composed kernel *is* the single wrapped convolution once the
    dilation front laps the torus."""
    rule = resolve_rule(rule)
    base = kernel_offsets(rule)
    h, w = int(shape[-2]), int(shape[-1])
    t = _require_span(t)
    if t == 0:
        return np.zeros((1, 2), dtype=np.int64)  # the identity kernel
    acc = _parity_dedup(base, (h, w))
    for bit in bin(t)[3:]:  # remaining bits below the MSB, high to low
        acc = _parity_dedup(2 * acc, (h, w))  # Frobenius: K² offsets = 2·offsets
        if bit == "1":
            _compose_guard(
                len(acc), len(base),
                what=f"fastforward kernel composition ({rule}, t={t}, "
                     f"{h}x{w})",
            )
            cand = (acc[None, :, :] + base[:, None, :]).reshape(-1, 2)
            acc = _parity_dedup(cand, (h, w))
    return acc


def kernel_plane(rule, t: int, shape: Tuple[int, int]) -> np.ndarray:
    """The T-step kernel rendered as a wrapped ``(H, W)`` uint8 plane
    (guard-priced): ``jump(board) == board ⊛ kernel_plane`` over GF(2).
    Row/col 0 is the zero offset (apply with ``apply_kernel``)."""
    h, w = int(shape[-2]), int(shape[-1])
    guard.require_intermediates_fit(
        h * w,
        what=f"fastforward kernel plane ({resolve_rule(rule)}, t={t}, {h}x{w})",
        detail="Use pow_offsets (sparse) or the factored fast_forward jump.",
        shapes=[((h, w), 1)],
    )
    plane = np.zeros((h, w), dtype=np.uint8)
    offs = pow_offsets(rule, t, (h, w))
    plane[offs[:, 0], offs[:, 1]] ^= 1
    return plane


def apply_offsets(board: jax.Array, offs: np.ndarray) -> jax.Array:
    """XOR-convolve a 0/1 board by a sparse offset kernel: one roll + XOR
    per set offset (``next[p] = XOR_o board[p + o]``).  The generic apply
    for materialized kernels — tests use it to check the composed kernel
    against iteration; the hot path is :func:`fast_forward`."""
    if len(offs) == 0:
        return jnp.zeros_like(board)
    acc = None
    for dy, dx in offs:
        term = (
            board
            if (dy % board.shape[-2], dx % board.shape[-1]) == (0, 0)
            else jnp.roll(board, (-int(dy), -int(dx)), axis=(-2, -1))
        )
        acc = term if acc is None else jnp.bitwise_xor(acc, term)
    return acc


# Bounded: serve clients control (rule, k, shape), so an unbounded cache
# would pin one jitted closure per distinct key for the process lifetime
# (the retained-compile hazard class GL-HAZ01 catches in method form).
# Eviction just recompiles a ~|K|-roll program on the next miss.
@functools.lru_cache(maxsize=2048)
def _jump_pow2_fn(rule_key, k: int, shape: Tuple[int, int]) -> Callable:
    """A jitted 2^k-epoch jump (cached per (rule, k, shape)): the base
    kernel with offsets scaled by 2^k (Frobenius), applied as ≤ |K| rolls
    + XORs in one device program.  Scaled offsets that collide mod the
    torus cancel in pairs (GF(2)), so the roll list is parity-deduped
    host-side before tracing."""
    rule = resolve_rule(rule_key)
    scaled = _scaled_offsets(kernel_offsets(rule), k, shape)
    shifts = [(int(dy), int(dx)) for dy, dx in scaled]

    @jax.jit
    def _run(board: jax.Array) -> jax.Array:
        return apply_offsets(board, np.asarray(shifts).reshape(-1, 2))

    h, w = int(shape[-2]), int(shape[-1])
    return registered_jit(
        "fastforward", ("jump_pow2", rule.name, k, shape), _run,
        # Effective work: one program advances 2^k epochs (the O(log T)
        # headline the /cost roofline is meant to surface); actual device
        # traffic is |shifts| rolls + XORs over one board.
        cost={
            "cells": float(h) * w * (2 ** k),
            "bytes": float(len(shifts) + 1) * h * w,
            "flops": float(len(shifts)) * h * w,
        },
    )


def fast_forward(board: jax.Array, rule, t: int) -> jax.Array:
    """Advance a dense 0/1 board ``t`` epochs under a linear rule in
    ``popcount(t)`` device programs — the factored jump (each set bit of
    ``t`` applies one Frobenius-scaled copy of the base kernel; the
    factors commute, so order is free).  Bit-identical to iterating ``t``
    steps; raises ``ValueError`` for non-linear rules."""
    rule = resolve_rule(rule)
    kernel_offsets(rule)  # the linearity proof/refusal, before any work
    t = _require_span(t)
    h, w = int(board.shape[-2]), int(board.shape[-1])
    out = board
    k = 0
    while t:
        if t & 1:
            out = _jump_pow2_fn(rule, k, (h, w))(out)
        t >>= 1
        k += 1
    return out


def fast_forward_np(board: np.ndarray, rule, t: int) -> np.ndarray:
    """Host-array convenience wrapper (the serve fast path's shape):
    numpy in, numpy out, device compute in between."""
    return np.asarray(fast_forward(jnp.asarray(board, dtype=jnp.uint8), rule, t))


def certify_jump(board, rule, t: int) -> int:
    """The jump-vs-iterate certificate: fast-forward ``board`` by ``t``
    AND iterate the same ``t`` through the ordinary dense stepper; their
    on-device digests must agree.  Returns the agreed digest; raises
    ``RuntimeError`` on divergence (a linearity-math or kernel bug — the
    caller must not trust the jump).  O(t) stepper work, so callers
    sample small t (the ``ff_certify_steps`` knob), never the full span."""
    from akka_game_of_life_tpu.ops import digest as odigest, stencil

    rule = resolve_rule(rule)
    board = jnp.asarray(board, dtype=jnp.uint8)
    jumped = fast_forward(board, rule, t)
    iterated = stencil.multi_step_fn(rule, t)(board) if t else board
    dfn = jax.jit(odigest.digest_dense)
    d_jump = odigest.value(np.asarray(dfn(jumped), dtype=np.uint32))
    d_iter = odigest.value(np.asarray(dfn(iterated), dtype=np.uint32))
    if d_jump != d_iter:
        raise RuntimeError(
            f"fast-forward certification failed for {rule} at t={t}: "
            f"jump digest {d_jump:016x} != iterate digest {d_iter:016x} — "
            f"refusing to trust the jump"
        )
    return d_jump


# -- the banded-matmul GF(2) lane (separable kernels: the Fredkin family) ------


def _pow1d_offsets(radius: int, t: int, n: int) -> np.ndarray:
    """The 1-D XOR-power mask: T-th GF(2) power of ``ones(2R+1)`` on the
    length-``n`` circle, as sorted residues — same square-and-multiply as
    :func:`pow_offsets`, one axis (trinomial coefficients mod 2 for R=1:
    the Sierpinski structure that keeps these masks sparse at 2^k)."""
    base = np.arange(-radius, radius + 1, dtype=np.int64)

    def dedup(vals: np.ndarray) -> np.ndarray:
        uniq, counts = np.unique(vals % n, return_counts=True)
        return uniq[counts % 2 == 1]

    if t == 0:
        return np.zeros(1, dtype=np.int64)
    acc = dedup(base)
    for bit in bin(t)[3:]:
        acc = dedup(2 * acc)
        if bit == "1":
            acc = dedup((acc[None, :] + base[:, None]).ravel())
    return acc


def _centered(residues: np.ndarray, n: int) -> np.ndarray:
    """Map circle residues to the centered range (-n//2, n//2]."""
    return ((residues + n // 2 - 1) % n) - (n // 2 - 1) if n > 1 else residues * 0


def _mask_slab(tile: int, centered: np.ndarray, s: int) -> np.ndarray:
    """(tile, tile + 2s) GEMM operand slab: row t has ones at columns
    t + s + o for each centered mask offset o — the PR 11 band slab with
    the contiguous ±R band generalized to an arbitrary 0/1 mask."""
    slab = np.zeros((tile, tile + 2 * s), np.float32)
    for off in centered:
        slab[np.arange(tile), np.arange(tile) + s + int(off)] = 1.0
    return slab


@functools.lru_cache(maxsize=64)  # keyed on raw t — bench/test lane, bounded
def jump_matmul_fn(rule_key, t: int, shape: Tuple[int, int], mode: str = "auto"):
    """The T-step jump as two blocked banded matrix multiplies over GF(2)
    — the MXU lane, for SEPARABLE linear kernels only (the full-box
    Fredkin family, whose kernel is ``ones ⊗ ones``; replicator-style
    center-less kernels are not rank-1 and take the roll path).

    ``W = parity(A_rows(T) · parity(S stage)) ``: the row pass sums each
    column's 1-D XOR-power window and reduces mod 2 *between* passes (so
    every GEMM accumulates counts ≤ board side, exactly representable on
    all three PR 11 dtype lanes), the column pass does the same along
    rows, and the epilogue takes the final parity.  Operands, pads, and
    slabs are guard-priced at closure-build time: once the mask wraps the
    torus the slabs approach (K, K + side) — the capped working set the
    issue's wrap story names."""
    from akka_game_of_life_tpu.ops.matmul_stencil import (
        _pick_tile,
        _resolve_mode,
    )

    rule = resolve_rule(rule_key)
    t = _require_span(t)
    kern = linear_kernel(rule)
    if kern is None or not kern.all():
        raise ValueError(
            f"rule {rule} has no separable (full-box) linear kernel; the "
            f"banded GF(2) matmul jump needs ones⊗ones — use fast_forward "
            f"(the factored roll path) instead"
        )
    h, w = int(shape[-2]), int(shape[-1])
    mode = _resolve_mode(mode)
    rows_c = _centered(_pow1d_offsets(rule.radius, t, h), h)
    cols_c = _centered(_pow1d_offsets(rule.radius, t, w), w)
    sr = int(np.max(np.abs(rows_c))) if len(rows_c) else 0
    sc = int(np.max(np.abs(cols_c))) if len(cols_c) else 0
    kr, kc = _pick_tile(h), _pick_tile(w)
    item = {"f32": 4, "int8": 1, "bf16": 2}[mode]
    planes = [
        ((h + 2 * sr, w), item),  # row-padded operand
        ((h, w), 4),  # row-pass counts (accumulator dtype)
        ((h, w + 2 * sc), item),  # col-padded parity plane
        ((h, w), 4),  # col-pass counts
        ((kr, kr + 2 * sr), item),  # row mask slab
        ((kc, kc + 2 * sc), item),  # col mask slab
    ]
    est = sum(guard.plane_bytes(s, i) for s, i in planes)
    guard.require_intermediates_fit(
        est,
        what=f"fastforward matmul jump ({rule}, t={t}, {h}x{w}, {mode})",
        detail="Use fast_forward (the factored roll path keeps working "
               "sets board-sized at any T).",
        shapes=planes,
    )
    od = {"f32": jnp.float32, "int8": jnp.int8, "bf16": jnp.bfloat16}[mode]
    acc_t = jnp.int32 if mode == "int8" else jnp.float32
    slab_r = jnp.asarray(_mask_slab(kr, rows_c, sr).astype(od))
    slab_ct = jnp.asarray(_mask_slab(kc, cols_c, sc).T.astype(od))

    def _dot(a, b):
        return jax.lax.dot_general(
            a, b, (((a.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=acc_t,
        )

    @jax.jit
    def _run(board: jax.Array) -> jax.Array:
        x = board.astype(od)
        xp = jnp.concatenate([x[h - sr:], x, x[:sr]], axis=0) if sr else x
        rows = [
            _dot(slab_r, jax.lax.dynamic_slice_in_dim(xp, c * kr, kr + 2 * sr, 0))
            for c in range(h // kr)
        ]
        # Parity BETWEEN passes: keeps the column GEMM's counts ≤ the
        # mask weight (< 2²⁴), exact on every dtype lane.
        y = (jnp.concatenate(rows, axis=0).astype(jnp.int32) & 1).astype(od)
        yp = jnp.concatenate([y[:, w - sc:], y, y[:, :sc]], axis=1) if sc else y
        cols = [
            _dot(jax.lax.dynamic_slice_in_dim(yp, c * kc, kc + 2 * sc, 1), slab_ct)
            for c in range(w // kc)
        ]
        out = jnp.concatenate(cols, axis=1).astype(jnp.int32) & 1
        return out.astype(board.dtype)

    return registered_jit(
        "fastforward", ("jump_matmul", rule.name, t, shape, mode), _run,
        # Effective cells: t epochs in one program; bytes from the guard-
        # priced plane estimate; flops from the two banded GEMM passes.
        cost={
            "cells": float(h) * w * t,
            "bytes": float(est),
            "flops": 2.0 * h * w * ((kr + 2 * sr) + (kc + 2 * sc)),
        },
    )


def jump_plan(rule, t: int, shape: Tuple[int, int]) -> dict:
    """What a jump will cost, as data (the serve admission path and bench
    report this): device programs, per-factor roll counts, support
    half-width, and whether the composed kernel has wrapped the torus.

    ``factor_rolls[i]`` is the set-cell count of the i-th scaled factor
    AFTER torus parity cancellation — on a 2^m-side torus a factor scaled
    by 2^k with k ≥ m collapses every offset onto the center, so a whole
    power-of-two jump can legitimately reduce to the zero/identity map
    (the odd-rule self-replication periodicity); the plan makes that
    visible so a benchmark can never pass a trivial program off as
    work."""
    rule = resolve_rule(rule)
    t = _require_span(t)
    base = kernel_offsets(rule)
    h, w = int(shape[-2]), int(shape[-1])
    s = support_radius(rule, t)
    factor_rolls = [
        int(len(_scaled_offsets(base, k, (h, w))))
        for k in range(max(1, int(t)).bit_length())
        if (t >> k) & 1
    ]
    return {
        "programs": max(1, bin(int(t)).count("1")),
        "rolls_per_program": int(len(base)),
        "factor_rolls": factor_rolls,
        "support_radius": s,
        "wrapped": 2 * s + 1 >= min(h, w),
    }
