"""Pallas TPU kernel for bit-plane CA (Generations / WireWorld) — the
multi-state twin of :mod:`akka_game_of_life_tpu.ops.pallas_stencil`, built
on the shared temporally-blocked sweep with each plane fed as its OWN 2-D
operand (:func:`pallas_stencil.temporal_sweep_planes_fn`).

Each grid step loads ``block_rows + 2k`` packed rows of every plane into
VMEM as plain 2-D blocks, advances the central ``block_rows`` by ``k``
generations with :func:`bitpack_gen.step_gen_padded_rows_planes`
(shared-row alive sums; ripple-carry refractory decay or the wireworld
plane transition), and writes back — HBM sees one read and one write of
each (H, W/32) plane per sweep.

An earlier revision carried the planes as one stacked (m, H, W/32) array
through the single-array sweep's ``n_prefix=1`` path; on hardware that
measured *slower* than the XLA plane scan (2.81 vs 3.19×10¹⁰ at 8192²,
`artifacts/tpu_session_r3b/bench-full.log`) while the binary kernel's 2-D
blocks ran 1.82×10¹² — hence the per-plane operand layout.  The public
interface stays stacked: (m, H, W/32) in, (m, H, W/32) out, with the
tuple↔stack conversion paid once per jitted call, not per sweep.

Reference capability note: this is the multi-state-family end point of
collapsing the reference's per-cell actor protocol
(``CellActor.scala:63-89``) into on-chip arithmetic — refractory decay
included, which the reference's single hard-coded rule
(``NextStateCellGathererActor.scala:44``) never had.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from akka_game_of_life_tpu.ops.bitpack_gen import (
    n_planes,
    step_gen_padded_rows_planes,
)
from akka_game_of_life_tpu.ops.pallas_stencil import (
    DEFAULT_STEPS_PER_SWEEP,
    auto_steps_per_sweep,
    temporal_sweep_planes_fn,
)
from akka_game_of_life_tpu.ops.rules import resolve_rule

DEFAULT_BLOCK_ROWS = 64


def gen_sweep_fn(
    rule,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: int = DEFAULT_STEPS_PER_SWEEP,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[tuple], tuple]:
    """One Pallas sweep advancing a tuple of m (H, W/32) packed planes by
    ``steps_per_sweep`` generations (each plane its own 2-D operand)."""
    rule = resolve_rule(rule)
    return temporal_sweep_planes_fn(
        lambda exts: step_gen_padded_rows_planes(exts, rule),
        n_planes=n_planes(rule.states),
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )


@functools.lru_cache(maxsize=None)
def gen_pallas_multi_step_fn(
    rule_key,
    n_steps: int,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: Optional[int] = None,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Jitted n-step plane advance from temporally-blocked sweeps
    (defaulting ``steps_per_sweep`` like the binary kernel).  Stacked
    (m, H, W/32) in and out — the tuple form lives inside the jit."""
    rule = resolve_rule(rule_key)
    m = n_planes(rule.states)
    if steps_per_sweep is None:
        steps_per_sweep = auto_steps_per_sweep(n_steps, block_rows)
    if n_steps % steps_per_sweep:
        raise ValueError(
            f"n_steps={n_steps} not a multiple of steps_per_sweep={steps_per_sweep}"
        )
    sweep = gen_sweep_fn(
        rule,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    @jax.jit
    def run(planes: jax.Array) -> jax.Array:
        if planes.shape[0] != m:
            raise ValueError(f"expected {m} planes for {rule.states} states")

        def body(ps, _):
            return sweep(ps), None

        out, _ = jax.lax.scan(
            body,
            tuple(planes[k] for k in range(m)),
            None,
            length=n_steps // steps_per_sweep,
        )
        return jnp.stack(out)

    from akka_game_of_life_tpu.obs.programs import registered_jit

    return registered_jit(
        "pallas_gen", ("multi_step", rule.name, n_steps, block_rows), run,
        # m packed planes encode one board: one board of cells per step,
        # m planes of byte traffic per sweep.
        cost=lambda planes: {
            "cells": float(planes.shape[-2])
            * planes.shape[-1] * planes.dtype.itemsize * 8 * n_steps,
            "bytes": 2.0 * planes.size * planes.dtype.itemsize
            * (n_steps // steps_per_sweep),
            "flops": 4.0 * planes.size * planes.dtype.itemsize * 8 * n_steps,
        },
    )
