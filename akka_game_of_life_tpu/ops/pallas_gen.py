"""Pallas TPU kernel for bit-plane Generations CA — the multi-state twin of
:mod:`akka_game_of_life_tpu.ops.pallas_stencil`, built on the same shared
temporally-blocked sweep (:func:`pallas_stencil.temporal_sweep_fn`) with the
plane stack's leading ``m`` axis carried whole in every block.

Each grid step loads ``block_rows + 2k`` packed rows of every plane into
VMEM, advances the central ``block_rows`` by ``k`` generations with
:func:`bitpack_gen.step_gen_padded_rows` (shared-row alive sums,
ripple-carry refractory decay), and writes back — HBM sees one read and one
write of the (m, H, W/32) plane stack per sweep.

Reference capability note: this is the Generations-family end point of
collapsing the reference's per-cell actor protocol
(``CellActor.scala:63-89``) into on-chip arithmetic — multi-state decay
included, which the reference's single hard-coded rule
(``NextStateCellGathererActor.scala:44``) never had.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from akka_game_of_life_tpu.ops.bitpack_gen import n_planes, step_gen_padded_rows
from akka_game_of_life_tpu.ops.pallas_stencil import (
    DEFAULT_STEPS_PER_SWEEP,
    auto_steps_per_sweep,
    temporal_sweep_fn,
)
from akka_game_of_life_tpu.ops.rules import resolve_rule

DEFAULT_BLOCK_ROWS = 64


def gen_sweep_fn(
    rule,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: int = DEFAULT_STEPS_PER_SWEEP,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """One Pallas sweep advancing (m, H, W/32) packed planes by
    ``steps_per_sweep`` generations."""
    rule = resolve_rule(rule)
    m = n_planes(rule.states)
    inner = temporal_sweep_fn(
        lambda ext: step_gen_padded_rows(ext, rule),
        n_prefix=1,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    def sweep(planes: jax.Array) -> jax.Array:
        if planes.shape[0] != m:
            raise ValueError(f"expected {m} planes for {rule.states} states")
        return inner(planes)

    return sweep


@functools.lru_cache(maxsize=None)
def gen_pallas_multi_step_fn(
    rule_key,
    n_steps: int,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    steps_per_sweep: Optional[int] = None,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Jitted n-step Generations advance from temporally-blocked sweeps
    (defaulting ``steps_per_sweep`` like the binary kernel)."""
    rule = resolve_rule(rule_key)
    if steps_per_sweep is None:
        steps_per_sweep = auto_steps_per_sweep(n_steps, block_rows)
    if n_steps % steps_per_sweep:
        raise ValueError(
            f"n_steps={n_steps} not a multiple of steps_per_sweep={steps_per_sweep}"
        )
    sweep = gen_sweep_fn(
        rule,
        block_rows=block_rows,
        steps_per_sweep=steps_per_sweep,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    @jax.jit
    def run(planes: jax.Array) -> jax.Array:
        def body(s, _):
            return sweep(s), None

        out, _ = jax.lax.scan(body, planes, None, length=n_steps // steps_per_sweep)
        return out

    return run
