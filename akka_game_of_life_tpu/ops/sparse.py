"""Activity-gated sparse stepping: O(activity) work on dilute boards.

Every dense kernel in ``ops/`` does O(area) work per epoch — a handful of
gliders on an otherwise-dead torus costs the same as a fully boiling one.
Casper (PAPERS.md) frames the stencil bottleneck as memory traffic; the
cheapest byte is the one never touched, so this engine tracks WHICH parts
of the board changed and steps only those.

The unit of gating is a coarse **block** (``block`` cells square, one bit
per block).  The invariant that makes skipping exact, not approximate:

    A cell whose entire radius-``k`` neighborhood is identical at two
    consecutive chunk boundaries computes the identical next state — so a
    cell can change during chunk ``t+1`` only if some cell within ``k``
    of it changed during chunk ``t`` (``k`` = steps per chunk, radius-1
    rules).  With ``k <= block``, that influence front stays within one
    block ring: ``active(t+1) ⊆ dilate3x3(active(t))``.

Per chunk the stepper therefore (1) dilates last chunk's changed-block
bitmap by one block ring (toroidal 3×3 OR), (2) gathers the active blocks
with a ``k``-cell halo into a ``[n, B+2k, B+2k]`` batch, (3) advances the
batch ``k`` toroidal steps under one vmapped jit (the cut-edge garbage
front moves one cell per step, so the ``B×B`` interiors are exact — the
same slab argument as the cluster's chunk engine), (4) scatters the
interiors back and records which blocks actually changed.  Batch sizes
quantize to powers of two so the traffic mix compiles O(log blocks)
programs, not one per activity level (the serve-plane discipline).

Dense escape hatch: once the dilated active fraction crosses
``threshold`` the whole board steps through the ordinary dense chunk and
only the changed-block bitmap is recomputed (one vectorized compare) —
on a boiling board the gating costs one O(area) memcmp per chunk, a few
percent, never a per-block Python loop.

The first chunk after construction — and after any board the stepper did
not itself produce (checkpoint restore, crash replay) — runs dense with
every block considered active, so no change can ever be missed.

Host-orchestrated on purpose: the gather/scatter runs in numpy on the
host board while only the active slabs visit the accelerator.  That is
the right trade on dilute boards (the win this engine exists for);
``threshold`` hands boiling boards back to the dense device path.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from akka_game_of_life_tpu.ops.rules import resolve_rule


def pick_block(height: int, width: int, requested: int) -> int:
    """The effective gating block: the largest common divisor of the board
    sides that is <= ``requested`` (so blocks always tile the torus
    exactly).  Deterministic; 1 in the worst (coprime-sides) case."""
    g = math.gcd(height, width)
    best = 1
    for d in range(1, int(math.isqrt(g)) + 1):
        if g % d == 0:
            for c in (d, g // d):
                if c <= requested and c > best:
                    best = c
    return best


def dilate3x3(active: np.ndarray) -> np.ndarray:
    """Toroidal 3×3 OR-dilation of a bool block bitmap."""
    out = active.copy()
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if (dy, dx) != (0, 0):
                out |= np.roll(active, (dy, dx), axis=(0, 1))
    return out


def changed_blocks(prev: np.ndarray, new: np.ndarray, block: int) -> np.ndarray:
    """Bool (H//block, W//block) bitmap of blocks whose cells differ."""
    h, w = prev.shape
    nbh, nbw = h // block, w // block
    diff = prev != new
    return diff.reshape(nbh, block, nbw, block).any(axis=(1, 3))


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1) — the batch/length quantizer
    that bounds how many programs a varying traffic mix can compile.  The
    canonical copy; :mod:`serve.batch` re-exports it for the serving
    plane's size classes."""
    return 1 << max(0, int(n - 1).bit_length())


class SparseStepper:
    """Stateful activity-gated chunk engine for one board.

    ``step(board, k)`` advances a host uint8 board ``k`` generations.
    State: the changed-block bitmap of the last chunk, keyed to the array
    object the stepper produced — a board it has never seen resets the
    gate to all-active, which is what makes checkpoint restore / crash
    replay correct without any explicit hook.

    **Ownership contract**: a board the stepper itself produced is updated
    IN PLACE on the sparse path (every active slab is gathered — copied —
    before any block is written back, so the Jacobi semantics are exact);
    a foreign board is never mutated — its first chunk runs dense, which
    allocates the owned output.  Skipping the O(area) copy is the point:
    at 16384² the copy alone rivals the in-cache packed kernel, and the
    sparse path must cost O(activity), not O(area).  Callers that retain
    a reference across chunks (checkpoint writers, deferred observation)
    must copy — :class:`runtime.simulation.Simulation` does exactly that
    at its escape points."""

    def __init__(
        self,
        rule,
        shape,
        *,
        block: int = 128,
        threshold: float = 0.5,
    ) -> None:
        self.rule = resolve_rule(rule)
        if self.rule.radius != 1:
            raise ValueError(
                f"sparse stepping gates radius-1 rules; {self.rule} "
                f"(radius {self.rule.radius}) runs on the dense kernels"
            )
        self.shape = tuple(shape)
        self.block = pick_block(self.shape[0], self.shape[1], block)
        self.threshold = threshold
        self.grid = (self.shape[0] // self.block, self.shape[1] // self.block)
        self._changed: Optional[np.ndarray] = None
        self._last_out: Optional[np.ndarray] = None
        # Consecutive dense-fallback chunks: on a boiling board the bitmap
        # is recomputed only every other dense chunk (skipping it means
        # "assume everything active" — an over-approximation, so still
        # exact), halving the gate's dense-path tax.
        self._dense_streak = 0
        # Compiled cores, cached per (kind, steps) ON THE INSTANCE — an
        # lru_cache on the methods would key on `self` and pin every
        # stepper (and its retained full board) in a class-level cache for
        # the life of the process (the Simulation._steppers discipline).
        self._fns = {}
        # Gating observability, read by the embedder after each chunk.
        self.last_active_blocks = 0
        self.last_stepped_blocks = 0
        self.dense_chunks = 0
        self.sparse_chunks = 0

    @property
    def total_blocks(self) -> int:
        return self.grid[0] * self.grid[1]

    # -- jitted cores (cached per (steps, batch/board shape)) ----------------

    def _block_fn(self, steps: int):
        if ("block", steps) in self._fns:
            return self._fns[("block", steps)]
        import jax
        import jax.numpy as jnp

        from akka_game_of_life_tpu.ops.stencil import step as stencil_step

        rule = self.rule
        b = self.block

        def chunk(slab):
            # Toroidal scan on the (B+2k, B+2k) slab: the wrap only ever
            # corrupts the outermost halo cells (cut edges), whose garbage
            # front moves one cell per step — with steps <= k the B×B
            # interior slice is exact.  The per-block changed flag rides
            # the same fused pass, so the host never compares cells.
            out, _ = jax.lax.scan(
                lambda s, _: (stencil_step(s, rule), None),
                slab, None, length=steps,
            )
            interior = out[steps : steps + b, steps : steps + b]
            changed = jnp.any(interior != slab[steps : steps + b, steps : steps + b])
            return interior, changed

        from akka_game_of_life_tpu.obs.programs import registered_jit

        fn = self._fns[("block", steps)] = registered_jit(
            "sparse",
            ("block", self.rule.name, steps, self.block),
            jax.jit(jax.vmap(chunk)),
            # slabs: (n, B+2k, B+2k); the gated win is that n is the
            # ACTIVE block count, not the board's.
            cost=lambda slabs: {
                "cells": float(slabs.shape[0]) * b * b * steps,
                "bytes": 2.0 * slabs.size * slabs.dtype.itemsize,
                "flops": 18.0 * slabs.shape[0] * b * b * steps,
            },
        )
        return fn

    def _dense_fn(self, steps: int):
        if ("dense", steps) in self._fns:
            return self._fns[("dense", steps)]
        import jax

        from akka_game_of_life_tpu.ops.stencil import multi_step

        rule = self.rule
        b = self.block
        nbh, nbw = self.grid

        @jax.jit
        def run(board):
            out = multi_step(board, rule, steps)
            # The changed-block bitmap in the SAME fused device pass as the
            # step — a host-side O(area) compare per chunk would cost ~12%
            # of a boiling chunk (measured at 8192²); fused, the gate's
            # dense-path overhead stays within the <=5% budget.
            diff = out != board
            bitmap = diff.reshape(nbh, b, nbw, b).any(axis=(1, 3))
            return out, bitmap

        from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost

        run = self._fns[("dense", steps)] = registered_jit(
            "sparse",
            ("dense", self.rule.name, steps, self.shape),
            run,
            cost=lambda board: stencil_cost(
                board.shape[-2], board.shape[-1], steps
            ),
        )
        return run

    def _dense_plain_fn(self, steps: int):
        if ("plain", steps) not in self._fns:
            from akka_game_of_life_tpu.ops.stencil import multi_step_fn

            self._fns[("plain", steps)] = multi_step_fn(self.rule, steps)
        return self._fns[("plain", steps)]

    # -- the chunk ------------------------------------------------------------

    def step(self, board: np.ndarray, steps: int) -> np.ndarray:
        if steps < 1:
            return board
        if steps > self.block:
            raise ValueError(
                f"chunk of {steps} steps exceeds the {self.block}-cell "
                f"gating block: the one-ring dilation would miss influence "
                f"(use steps_per_call <= sparse_block)"
            )
        board = np.asarray(board, dtype=np.uint8)
        if board.shape != self.shape:
            raise ValueError(f"board {board.shape} != stepper {self.shape}")
        owned = self._last_out is not None and board is self._last_out
        if not owned:
            # Unknown provenance (first chunk, restore, replay): everything
            # is presumed active — the gate can only ever skip work it has
            # proven dead — and the board is not ours to mutate.
            self._dense_streak = 0
            active = np.ones(self.grid, dtype=bool)
        elif self._changed is None:
            # The previous dense chunk skipped its bitmap (hysteresis):
            # assume everything active.
            active = np.ones(self.grid, dtype=bool)
        else:
            active = dilate3x3(self._changed)
        n_active = int(active.sum())
        self.last_active_blocks = n_active
        if n_active > self.threshold * self.total_blocks:
            self._dense_streak += 1
            # Odd streaks (the first dense chunk included) compute the
            # bitmap, so a dilute board transitions to the sparse path
            # immediately; even streaks skip it — a boiling board pays the
            # fused diff every OTHER chunk, not every chunk.
            out = self._dense_step(
                board, steps, with_bitmap=self._dense_streak % 2 == 1
            )
        else:
            self._dense_streak = 0
            # In place only when the owned board is also writable: a dense
            # fallback chunk hands back a read-only zero-copy view of the
            # device result (copying every boiling chunk would be pure
            # overhead), so the first sparse chunk after one pays a single
            # transition copy and owns writable memory from then on.
            out = self._sparse_step(
                board, steps, active,
                inplace=owned and bool(board.flags.writeable),
            )
        self._last_out = out
        return out

    def _dense_step(
        self, board: np.ndarray, steps: int, with_bitmap: bool = True
    ) -> np.ndarray:
        # asarray on purpose: the jit result comes back as a read-only
        # zero-copy view, and copying it every boiling chunk would be the
        # exact O(area) tax the threshold exists to avoid — the sparse
        # path checks writability and pays one transition copy instead.
        if with_bitmap:
            out, bitmap = self._dense_fn(steps)(board)
            self._changed = np.asarray(bitmap)
        else:
            out = self._dense_plain_fn(steps)(board)
            self._changed = None
        out = np.asarray(out, dtype=np.uint8)
        self.last_stepped_blocks = self.total_blocks
        self.dense_chunks += 1
        return out

    def _sparse_step(
        self, board: np.ndarray, steps: int, active: np.ndarray,
        inplace: bool = False,
    ) -> np.ndarray:
        b, k = self.block, steps
        h, w = self.shape
        idx = np.argwhere(active)
        self.last_stepped_blocks = len(idx)
        self.sparse_chunks += 1
        if len(idx) == 0:
            # Provably a fixed point: nothing changed last chunk anywhere.
            self._changed = active
            return board
        # Gather each active block with its k-cell toroidal halo.  Two
        # mod-indexed takes per block keep the copies O(active area) — a
        # wrap-pad of the whole board would be O(area) and defeat the point.
        # Every slab is a COPY made before any write below, so the in-place
        # scatter cannot feed one block's new state into another's input.
        rows = (idx[:, 0, None] * b + np.arange(-k, b + k)[None, :]) % h
        cols = (idx[:, 1, None] * b + np.arange(-k, b + k)[None, :]) % w
        slabs = board[rows[:, :, None], cols[:, None, :]]
        # Quantize the batch dim to a power of two so activity churn reuses
        # O(log blocks) compiled programs; the padding rows recompute block
        # 0 and are dropped on scatter.
        n = len(idx)
        pad = next_pow2(n) - n
        if pad:
            slabs = np.concatenate([slabs, slabs[:1].repeat(pad, axis=0)])
        outs, flags = self._block_fn(k)(slabs)
        outs = np.asarray(outs, dtype=np.uint8)[:n]
        flags = np.asarray(flags)[:n]
        # In place when we own the board (see the class docstring) — the
        # O(area) defensive copy would otherwise dominate dilute chunks.
        out = board if inplace else board.copy()
        changed = np.zeros(self.grid, dtype=bool)
        for i, (by, bx) in enumerate(idx):
            if not flags[i]:
                continue  # device-computed: this block did not change
            y0, x0 = by * b, bx * b
            out[y0 : y0 + b, x0 : x0 + b] = outs[i]
            changed[by, bx] = True
        self._changed = changed
        return out
