"""Shared intermediate-size guard: refuse loudly, never allocate-and-die.

The recorded LtL OOM lesson (``ops/ltl.py``, ``artifacts/tpu_session_r3b``):
an 8192² radius-5 board once materialized a 17.2 GB conv intermediate and
killed the run *after* the allocator had already committed — the failure
surfaced as a device OOM deep inside XLA instead of a config error naming
the knob.  Every kernel family that materializes off-board intermediates
(the LtL shift-add count planes, the banded-matmul operands and products)
now prices them *up front*, at trace/closure-build time, through this one
helper: estimate the bytes, compare against a configurable cap, and raise
a ``ValueError`` that names the shapes, the cap, and the knob that raises
it — before anything is allocated.

The cap is deliberately coarse (it bounds *planned* scratch, not a
promise about allocator behavior) and generous by default: it exists to
catch the two-orders-of-magnitude surprises, not to haggle over 10%.
"""

from __future__ import annotations

import os
from typing import Iterable, Tuple

# Environment override, in MiB.  The default covers every intermediate this
# repo's kernels plan at the flagship shapes on a 16 GB v5e HBM or this
# host's RAM, while refusing the pathological (full-board conv padding,
# no-divisor full-band matrices at 65536²) before the allocator sees them.
CAP_ENV = "GOL_INTERMEDIATE_CAP_MB"
DEFAULT_CAP_MB = 8192


def intermediate_cap_bytes() -> int:
    """The active cap in bytes (``GOL_INTERMEDIATE_CAP_MB`` or the
    default).  Read per call — tests and operators can flip the env var
    without reimporting kernels."""
    try:
        mb = int(os.environ.get(CAP_ENV, DEFAULT_CAP_MB))
    except ValueError:
        raise ValueError(
            f"{CAP_ENV}={os.environ.get(CAP_ENV)!r} is not an integer MiB count"
        ) from None
    return mb * 2**20


def plane_bytes(shape: Tuple[int, ...], itemsize: int) -> int:
    """Bytes of one dense plane of ``shape`` at ``itemsize`` bytes/element."""
    total = itemsize
    for dim in shape:
        total *= int(dim)
    return total


def nearest_3smooth(n: int) -> int:
    """The smallest 3-smooth width (2^a · 3^b with b ≥ 1 and a ≥ 5, so
    32 | width keeps every packed kernel eligible) that is ≥ ``n`` — the
    pad target refusal messages suggest when a power-of-two board width
    caps the matmul family's f32 digit-packing depth at 2.

    The documented PR 11 residue this makes discoverable at the point of
    failure: digit depth must *divide* the width, so 2^k widths only admit
    depths {1, 2, 4, ...} and the mantissa budget caps them at 2 for
    R ≥ 5, while a width with a factor of 3 reaches depth 3–6 (the
    ``bench_suite`` config 15 LtL sweep runs at 12288 = 2¹²·3 for exactly
    this reason)."""
    if n < 1:
        raise ValueError(f"width must be positive, got {n}")
    best = None
    b = 1
    while 3**b <= max(n, 96) * 2:
        a = 5
        while (3**b) << a < n:
            a += 1
        cand = (3**b) << a
        if best is None or cand < best:
            best = cand
        b += 1
    return best


def require_intermediates_fit(
    estimated_bytes: int,
    *,
    what: str,
    detail: str = "",
    shapes: Iterable[Tuple[Tuple[int, ...], int]] = (),
) -> None:
    """Raise ``ValueError`` if ``estimated_bytes`` exceeds the cap.

    ``what`` names the kernel/path (appears first in the message);
    ``detail`` adds the actionable remedy beyond raising the cap;
    ``shapes`` optionally itemizes (shape, itemsize) planes for the
    message so the operator sees *which* intermediate blew up.
    """
    cap = intermediate_cap_bytes()
    if estimated_bytes <= cap:
        return
    itemized = "; ".join(
        f"{tuple(s)}x{i}B={plane_bytes(s, i) / 2**20:.0f}MiB" for s, i in shapes
    )
    raise ValueError(
        f"{what} would materialize ~{estimated_bytes / 2**20:.0f} MiB of "
        f"intermediates, over the {cap / 2**20:.0f} MiB cap"
        + (f" ({itemized})" if itemized else "")
        + " — refusing up front instead of allocate-and-die (the recorded "
        f"LtL OOM lesson, ops/ltl.py). "
        + (f"{detail} " if detail else "")
        + f"Raise {CAP_ENV} (MiB) to override."
    )
