"""Bit-plane SWAR stepping for multi-state CA: *Generations* and *WireWorld*.

The binary bit-packed kernel (:mod:`akka_game_of_life_tpu.ops.bitpack`)
cannot express refractory states, so Generations rules (Brian's Brain /2/3,
Star Wars 345/2/4 — BASELINE config 4) previously ran only on the dense
uint8 path at 1 byte/cell.  Here a cell's state (0=dead, 1=alive, 2..S-1
refractory, decaying upward and wrapping to 0 — ops/rules.py semantics) is
stored in ``m = ceil(log2(S))`` packed bit planes, 32 cells per uint32 lane
per plane, so Brian's Brain is 2 bits/cell and anything up to 255 states
stays ≤ 8 bits/cell with all transition logic as plane-wise SWAR:

- the *alive* plane (state == 1) feeds the same shared-row-sum Moore counter
  as the binary kernel (``bitpack._row_triple_sum`` / ``_count_bits``);
- birth/survive hits come from the count-equality predicate planes;
- refractory decay is a ripple-carry increment over the m planes with a
  wrap-to-zero mask at state S-1.

Transition (matching runtime/actor_engine.py's ``Gatherer.result`` and the
dense kernel): dead → 1 on birth-hit else 0; alive → 1 on survive-hit else
state+1 (=2); refractory → state+1, wrapping S-1 → 0.  The alive center
contributes +1 to its own count, so survive thresholds shift by +1 exactly
as in the binary kernel; a dead or refractory center contributes 0.

*WireWorld* (``Rule.kind="wireworld"``, 4 states: 0 empty, 1 electron head,
2 tail, 3 conductor) shares the whole pipeline — the counted plane is
state==1 (heads) either way — and its transition is *cheaper* than
Generations': with the state's two bits as planes (p0, p1), head=01,
tail=10, conductor=11, the rules "head→tail, tail→conductor,
conductor→head iff head-count ∈ birth, empty stays" collapse to::

    next_p0 = p1                                    # tail|conductor gain p0
    next_p1 = (p0 ^ p1) | (p0 & p1 & ~excite)       # head|tail | calm conductor

where ``excite`` is the birth-count predicate with NO +1 shift (a conductor
center is not a head, so it never contributes to its own count).  The dense
kernel (``ops/stencil.py apply_rule``) and the actor engines implement the
same transition per-cell; ``tests/test_wireworld.py`` pins all three equal.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.ops.bitpack import (
    _count_bits,
    _row_triple_sum,
    count_eq_fn,
    pack,
    unpack,
)
from akka_game_of_life_tpu.obs.programs import registered_jit
from akka_game_of_life_tpu.ops.rules import resolve_rule


def _require_plane_support(rule) -> None:
    """The plane steppers encode Generations decay and WireWorld transition
    semantics; radius-R LtL (binary, but wider than the Moore-8 adders)
    rides :mod:`akka_game_of_life_tpu.ops.ltl` instead."""
    if not (rule.is_totalistic or rule.kind == "wireworld"):
        raise ValueError(
            f"bit-plane kernel supports totalistic and wireworld rules "
            f"only, got {rule}"
        )


def n_planes(states: int) -> int:
    return max(1, (states - 1).bit_length())


def pack_gen(grid, states: int) -> jax.Array:
    """(H, W) uint8 states → (m, H, W/32) uint32 bit planes, LSB plane first."""
    grid = jnp.asarray(grid, dtype=jnp.uint8)
    if states > 2 ** 8:
        raise ValueError("states > 256 not supported")
    planes = [pack((grid >> k) & 1) for k in range(n_planes(states))]
    return jnp.stack(planes)


def unpack_gen(planes: jax.Array) -> jax.Array:
    """(m, H, W/32) uint32 → (H, W) uint8."""
    out = None
    for k in range(planes.shape[0]):
        part = unpack(planes[k]) << k
        out = part if out is None else out | part
    return out


def pack_gen_np(grid: np.ndarray, states: int) -> np.ndarray:
    """Host-side :func:`pack_gen` twin: (H, W) uint8 → (m, H, W/32) uint32."""
    from akka_game_of_life_tpu.ops.bitpack import pack_np

    if states > 2 ** 8:
        raise ValueError("states > 256 not supported")
    grid = np.asarray(grid, dtype=np.uint8)
    return np.stack(
        [pack_np((grid >> k) & 1) for k in range(n_planes(states))]
    )


def unpack_gen_np(planes: np.ndarray) -> np.ndarray:
    """Host-side :func:`unpack_gen` twin: (m, H, W/32) uint32 → (H, W) uint8."""
    from akka_game_of_life_tpu.ops.bitpack import unpack_np

    out = None
    for k in range(planes.shape[0]):
        part = unpack_np(planes[k]) << k
        out = part if out is None else out | part
    return out.astype(np.uint8)


def _eq_const(planes: List[jax.Array], value: int) -> jax.Array:
    """Plane where the m-bit state equals ``value``."""
    t = None
    for k, p in enumerate(planes):
        bit = p if (value >> k) & 1 else ~p
        t = bit if t is None else t & bit
    return t


def _increment(planes: List[jax.Array]) -> List[jax.Array]:
    """state+1 over m bit planes (ripple carry; overflow discarded — the
    wrap mask below zeroes the only state that can overflow)."""
    out = []
    carry = None
    for p in planes:
        if carry is None:
            out.append(~p)
            carry = p
        else:
            out.append(p ^ carry)
            carry = p & carry
    return out


def _transition(
    ps_center: List[jax.Array],
    alive_c: jax.Array,
    dead_c: jax.Array,
    eq,
    rule,
) -> List[jax.Array]:
    """Next-state planes (as a list) from center-row plane slices plus
    count predicates (shared by the toroidal and padded-rows steppers)."""
    birth = jnp.uint32(0)
    for n in rule.birth:
        birth = birth | eq(n)  # dead center: count has no self term
    survive = jnp.uint32(0)
    for n in rule.survive:
        survive = survive | eq(n + 1)  # alive center: +1 self term
    to_one = (dead_c & birth) | (alive_c & survive)
    # Everyone else: dead stays 0; alive/refractory increments, wrapping
    # S-1 → 0.  (alive+1 = 2 is exactly the "enters state 2" transition.)
    inc = _increment(ps_center)
    wrap = _eq_const(ps_center, rule.states - 1)
    advance = ~dead_c & ~to_one & ~wrap
    return [
        (to_one if k == 0 else jnp.uint32(0)) | (advance & inc[k])
        for k in range(len(ps_center))
    ]


def _transition_wire(ps_center: List[jax.Array], eq, rule) -> List[jax.Array]:
    """Next-state WireWorld planes (as a list) from center-row plane slices
    plus count predicates (see the module docstring's derivation).  Far
    cheaper than the Generations transition: two plane expressions on top
    of the shared head count."""
    p0, p1 = ps_center
    excite = jnp.uint32(0)
    for n in rule.birth:  # {1, 2}: conductor center never self-counts
        excite = excite | eq(n)
    return [p1, (p0 ^ p1) | (p0 & p1 & ~excite)]


def step_gen_padded_rows_planes(
    ps: List[jax.Array], rule
) -> List[jax.Array]:
    """One plane step (Generations or WireWorld) on ``m`` separate
    row-padded 2-D slabs: each (h+2, words) with one halo row top and
    bottom → m × (h, w).  Row triple sums of the counted plane (state==1:
    alive / electron heads) are computed once per slab row and shared
    across the three output rows each feeds — the multi-state twin of
    :func:`akka_game_of_life_tpu.ops.bitpack.step_padded_rows`.  The
    Pallas plane sweep feeds each plane as its own 2-D operand (clean 2-D
    VMEM blocks, no stacked leading dim), so the list form is the kernel
    primitive and the stacked form below wraps it."""
    rule = resolve_rule(rule)
    _require_plane_support(rule)
    m = n_planes(rule.states)
    if len(ps) != m:
        raise ValueError(f"expected {m} planes for {rule.states} states")
    alive = _eq_const(ps, 1)
    s, c = _row_triple_sum(alive)
    eq = count_eq_fn(
        *_count_bits(s[:-2], c[:-2], s[1:-1], c[1:-1], s[2:], c[2:])
    )
    center = [p[1:-1] for p in ps]
    if rule.kind == "wireworld":
        return _transition_wire(center, eq, rule)
    dead = _eq_const(ps, 0)
    return _transition(center, alive[1:-1], dead[1:-1], eq, rule)


def step_gen_padded_rows(padded: jax.Array, rule) -> jax.Array:
    """Stacked-form twin of :func:`step_gen_padded_rows_planes`:
    (m, h+2, words) → (m, h, words)."""
    rule = resolve_rule(rule)
    m = n_planes(rule.states)
    if padded.shape[0] != m:
        raise ValueError(f"expected {m} planes for {rule.states} states")
    return jnp.stack(
        step_gen_padded_rows_planes([padded[k] for k in range(m)], rule)
    )


def step_gen(planes: jax.Array, rule) -> jax.Array:
    """One toroidal plane step (Generations or WireWorld) on (m, H, W/32)
    packed planes."""
    rule = resolve_rule(rule)
    _require_plane_support(rule)
    m = n_planes(rule.states)
    if planes.shape[0] != m:
        raise ValueError(f"expected {m} planes for {rule.states} states")
    ps = [planes[k] for k in range(m)]

    alive = _eq_const(ps, 1)

    s, c = _row_triple_sum(alive)
    eq = count_eq_fn(
        *_count_bits(
            jnp.roll(s, 1, axis=0),
            jnp.roll(c, 1, axis=0),
            s,
            c,
            jnp.roll(s, -1, axis=0),
            jnp.roll(c, -1, axis=0),
        )
    )
    if rule.kind == "wireworld":
        return jnp.stack(_transition_wire(ps, eq, rule))
    dead = _eq_const(ps, 0)
    return jnp.stack(_transition(ps, alive, dead, eq, rule))


@functools.lru_cache(maxsize=None)
def gen_multi_step_fn(rule_key, n_steps: int) -> Callable[[jax.Array], jax.Array]:
    rule = resolve_rule(rule_key)

    @jax.jit
    def _run(planes: jax.Array) -> jax.Array:
        def body(p, _):
            return step_gen(p, rule), None

        out, _ = jax.lax.scan(body, planes, None, length=n_steps)
        return out

    return registered_jit(
        "bitpack_gen", ("multi_step", rule.name, n_steps), _run,
        # One board's worth of cells per step; the plane stack (planes.size)
        # is the byte traffic.
        cost=lambda planes: {
            "cells": float(planes.shape[-2])
            * planes.shape[-1] * planes.dtype.itemsize * 8 * n_steps,
            "bytes": 2.0 * planes.size * planes.dtype.itemsize * n_steps,
            "flops": 4.0 * planes.size * planes.dtype.itemsize * 8 * n_steps,
        },
    )
