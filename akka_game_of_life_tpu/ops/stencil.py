"""Dense stencil kernels: the TPU-native replacement for the per-cell actors.

One call to :func:`step` performs what the reference does with ~18·n network
messages per epoch (8 asks + 8 replies + gatherer spawn + state set + log per
cell — ``NextStateCellGathererActor.scala:32-45``, ``CellActor.scala:67-89``):
a fused Moore-neighbor count plus B/S thresholding over the whole grid, traced
once under ``jit`` and compiled by XLA into a single HBM-bandwidth-bound fused
loop.  Boundary semantics are **toroidal** (the intended capability per
BASELINE.json), not the reference's clipped-edge bug (``package.scala:24-25``).

The rule is closed over as a static Python value (two small int bitmasks), so
rule application is constant-folded into the stencil fusion — the rule *is*
data, never control flow.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule

STATE_DTYPE = jnp.uint8

# Moore-8 neighborhood offsets (dy, dx), self excluded — the same geometry as
# the reference's generateNeighbourAddresses (package.scala:17-28), minus its
# edge clipping.
MOORE_OFFSETS = tuple(
    (dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1) if (dy, dx) != (0, 0)
)


def neighbor_counts(alive: jax.Array) -> jax.Array:
    """Count live Moore neighbors on a torus.

    ``alive`` is a (H, W) uint8 0/1 indicator.  Implemented as a sum of eight
    ``jnp.roll`` shifts; XLA fuses the shifts+adds into one pass over the grid.
    """
    acc = jnp.zeros_like(alive)
    for dy, dx in MOORE_OFFSETS:
        acc = acc + jnp.roll(alive, shift=(dy, dx), axis=(0, 1))
    return acc


def neighbor_counts_padded(padded_alive: jax.Array) -> jax.Array:
    """Count live Moore neighbors given a tile pre-padded with a 1-cell halo.

    Input is (H+2, W+2); output is (H, W) valid-region counts.  This is the
    kernel used by the sharded runtime after the ppermute halo exchange, and by
    non-toroidal (clipped) boundary mode with a zero halo.
    """
    h = padded_alive.shape[-2] - 2
    w = padded_alive.shape[-1] - 2
    acc = jnp.zeros(padded_alive.shape[:-2] + (h, w), dtype=padded_alive.dtype)
    for dy in (0, 1, 2):
        for dx in (0, 1, 2):
            if (dy, dx) == (1, 1):
                continue
            acc = acc + jax.lax.slice_in_dim(
                jax.lax.slice_in_dim(padded_alive, dy, dy + h, axis=-2),
                dx,
                dx + w,
                axis=-1,
            )
    return acc


def apply_rule(state: jax.Array, counts: jax.Array, rule: Rule) -> jax.Array:
    """Apply an outer-totalistic rule given per-cell live-neighbor counts.

    Binary rules: next = survive-bit if alive else birth-bit.
    Generations rules (states > 2): a live cell that fails to survive enters
    the first refractory state (2) and decays to death; refractory cells block
    birth but do not count as neighbors.
    """
    c = counts.astype(jnp.uint32)
    birth = ((jnp.uint32(rule.birth_mask) >> c) & 1).astype(STATE_DTYPE)
    survive = ((jnp.uint32(rule.survive_mask) >> c) & 1).astype(STATE_DTYPE)
    if not rule.is_totalistic:  # wireworld (the only non-totalistic kind)
        # head → tail, tail → conductor, conductor → head iff the head
        # count hits the birth mask, empty stays.  counts already tallies
        # state==1 (heads) — the same pipeline as every other rule.
        return jnp.where(
            state == 1,
            jnp.asarray(2, STATE_DTYPE),
            jnp.where(
                state == 2,
                jnp.asarray(3, STATE_DTYPE),
                jnp.where((state == 3) & (birth == 1), jnp.asarray(1, STATE_DTYPE), state),
            ),
        )
    if rule.is_binary:
        return jnp.where(state == 1, survive, birth)
    one = jnp.asarray(1, STATE_DTYPE)
    two = jnp.asarray(2, STATE_DTYPE)
    decayed = jnp.where(state + 1 < rule.states, state + 1, 0).astype(STATE_DTYPE)
    live_next = jnp.where(survive == 1, one, two)
    return jnp.where(
        state == 0,
        birth,
        jnp.where(state == 1, live_next, decayed),
    )


def alive_mask(state: jax.Array) -> jax.Array:
    """0/1 live indicator (state == 1); identity layout for binary rules."""
    return (state == 1).astype(STATE_DTYPE)


def step(state: jax.Array, rule) -> jax.Array:
    """One toroidal CA step.  ``state`` is (H, W) uint8; rule may be a Rule,
    a known name, or a rulestring."""
    rule = resolve_rule(rule)
    if rule.kind == "ltl":
        from akka_game_of_life_tpu.ops import ltl

        return ltl.step_ltl(state, rule)
    counts = neighbor_counts(alive_mask(state))
    return apply_rule(state, counts, rule)


def step_padded(padded_state: jax.Array, rule: Rule) -> jax.Array:
    """One step on a tile pre-padded with a radius-deep halo:
    (H+2R, W+2R) → (H, W).  R is 1 for every kind except ltl."""
    if rule.kind == "ltl":
        from akka_game_of_life_tpu.ops import ltl

        return ltl.step_padded_ltl(padded_state, rule)
    counts = neighbor_counts_padded(alive_mask(padded_state))
    interior = padded_state[..., 1:-1, 1:-1]
    return apply_rule(interior, counts, rule)


@functools.lru_cache(maxsize=None)
def step_fn(rule_key: Rule) -> Callable[[jax.Array], jax.Array]:
    """A jitted single-step closure for a rule (cached per rule)."""
    rule = resolve_rule(rule_key)

    @jax.jit
    def _step(state: jax.Array) -> jax.Array:
        return step(state, rule)

    return registered_jit(
        "stencil", ("step", rule.name), _step,
        cost=lambda state: stencil_cost(state.shape[-2], state.shape[-1]),
    )


@functools.lru_cache(maxsize=None)
def step_fn_padded(rule_key: Rule) -> Callable[[jax.Array], jax.Array]:
    """A jitted halo-padded step closure: (h+2, w+2) → (h, w), cached per
    rule.  This is the per-tile engine for distributed workers."""
    rule = resolve_rule(rule_key)

    @jax.jit
    def _step(padded: jax.Array) -> jax.Array:
        return step_padded(padded, rule)

    return registered_jit(
        "stencil", ("step_padded", rule.name), _step,
        cost=lambda padded: stencil_cost(
            padded.shape[-2] - 2, padded.shape[-1] - 2
        ),
    )


def multi_step(state: jax.Array, rule, n_steps: int) -> jax.Array:
    """Advance ``n_steps`` generations under one jit trace via ``lax.scan``.

    The scan keeps the whole loop on-device: no host round-trip per epoch,
    unlike the reference's wall-clock tick broadcast (``BoardCreator.scala:107``).
    """
    rule = resolve_rule(rule)

    def body(s, _):
        return step(s, rule), None

    out, _ = jax.lax.scan(body, state, None, length=n_steps)
    return out


@functools.lru_cache(maxsize=None)
def multi_step_fn(rule_key: Rule, n_steps: int) -> Callable[[jax.Array], jax.Array]:
    """A jitted ``n_steps``-per-call closure (cached per (rule, n))."""
    rule = resolve_rule(rule_key)

    @jax.jit
    def _run(state: jax.Array) -> jax.Array:
        return multi_step(state, rule, n_steps)

    return registered_jit(
        "stencil", ("multi_step", rule.name, n_steps), _run,
        cost=lambda state: stencil_cost(
            state.shape[-2], state.shape[-1], n_steps
        ),
    )
