"""Larger-than-Life: radius-R window sums as separable VPU shift-adds.

Larger than Life (Evans) scales the neighborhood to a radius-R window —
the (2R+1)² Moore box (Golly NM) or the von Neumann diamond (NN).  The
obvious TPU mapping is a convolution on the MXU, and an earlier revision
of this module did exactly that — but a single-feature conv is the one
shape the TPU conv unit handles *badly*: XLA pads the lone channel to the
128-lane register width, so an 8192² radius-5 board materialized a 17.2 GB
intermediate and OOMed HBM (`artifacts/tpu_session_r3b/bench-full.log`).
A window sum is separable arithmetic, not matrix math, so it now runs the
way the rest of this framework computes — on the VPU with board-sized
intermediates:

- **box**: two separable shift-add passes (a (2R+1)-term column sum of
  row slices, then a (2R+1)-term row sum of column slices) — 2(2R+1)
  adds/cell that XLA fuses into single passes, peak scratch ≈ 2 planes
  of the count dtype;
- **diamond**: not separable, but each of its 2R+1 rows is a contiguous
  run, so one f32 row-cumsum turns every row's contribution into a
  two-slice difference — 2(2R+1) ops/cell instead of the O(R²) masked
  window, and exact (0/1 partial sums stay far below 2²⁴).

Counts ≤ max_neighbors ≤ 440 are exact in bf16 (integers to 256) when
they fit and in f32 beyond, chosen automatically.

The OOM lesson above is now enforced, not just remembered: both count
engines price their intermediates through the shared :mod:`ops/guard`
helper at trace/closure-build time and refuse over-cap shapes loudly
instead of allocate-and-die.  And the MXU formulation is back in a shape
that works: ``kernel=matmul`` delegates the radius-R window sum to the
banded matrix-multiply family (:mod:`ops/matmul_stencil`, ``A_R·S·A_Rᵀ``
evaluated block-diagonally — no single-channel conv padding, so no 17.2 GB
intermediate), which applies THIS module's rule tables, so the two paths
are bit-identical by construction.  Box neighborhoods only — the diamond
is not separable and stays on the cumsum path here.

The birth/survive sets are arbitrary subsets of 0..max_neighbors, applied as a
table gather (XLA lowers the tiny lookup into the fused epilogue).  With
R=1 this reduces exactly to the classic outer-totalistic step — the
cross-validation anchor ``tests/test_ltl.py`` pins against the VPU kernel.

Reference capability note: radius generalization is pure surplus over the
reference (one hard-coded radius-1 rule, ``NextStateCellGathererActor.scala:44``)
— it is here because the TPU-native design makes it nearly free.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost
from akka_game_of_life_tpu.ops import guard
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule

STATE_DTYPE = jnp.uint8


def _count_dtype(rule: Rule):
    # bf16 holds integers exactly to 256: enough for R<=7 ((2R+1)^2 <= 225).
    return jnp.bfloat16 if rule.max_neighbors < 255 else jnp.float32


def neighborhood_mask(radius: int, neighborhood: str) -> np.ndarray:
    """(2R+1, 2R+1) 0/1 window mask INCLUDING the center: the full box, or
    the von Neumann diamond (L1 ball)."""
    d = 2 * radius + 1
    if neighborhood == "diamond":
        yy, xx = np.mgrid[-radius : radius + 1, -radius : radius + 1]
        return (np.abs(yy) + np.abs(xx) <= radius).astype(np.uint8)
    return np.ones((d, d), np.uint8)


def _window_counts(
    alive_2d: jax.Array, radius: int, neighborhood: str, dtype
) -> jax.Array:
    """(H+2R, W+2R) 0/1 halo-padded alive plane → (H, W) window sums
    INCLUDING the center.

    Box: two separable shift-add passes over static slices (column sum then
    row sum) — no conv, so no TPU single-channel 128-lane padding and the
    peak intermediate is one (H, W+2R) plane of ``dtype``.

    Diamond: row dy of the L1 ball is a contiguous run of width
    2(R−|dy|)+1, so a single exclusive row-cumsum (f32 — exact: partial
    sums ≤ W+2R ≪ 2²⁴) turns each row's contribution into a two-slice
    difference; 2R+1 differences sum to the window.
    """
    r = radius
    d = 2 * r + 1
    ph, pw = alive_2d.shape
    h, w = ph - 2 * r, pw - 2 * r
    if neighborhood == "box":
        x = alive_2d.astype(dtype)
        col = x[0:h, :]
        for dy in range(1, d):
            col = col + x[dy : dy + h, :]  # (H, W+2R)
        out = col[:, 0:w]
        for dx in range(1, d):
            out = out + col[:, dx : dx + w]
        return out
    # Diamond (von Neumann L1 ball), via an exclusive row-cumsum.
    c = jnp.cumsum(alive_2d.astype(jnp.float32), axis=1)
    c = jnp.pad(c, ((0, 0), (1, 0)))  # c[i, j] = sum of alive[i, :j]
    out = jnp.zeros((h, w), jnp.float32)
    for dy in range(-r, r + 1):
        width = 2 * (r - abs(dy)) + 1
        lo = abs(dy)  # run starts at padded column x + |dy|
        rows = slice(r + dy, r + dy + h)
        out = out + (c[rows, lo + width : lo + width + w] - c[rows, lo : lo + w])
    return out.astype(dtype)


def _tables(rule: Rule):
    n = rule.max_neighbors + 1
    birth = np.zeros(n, np.uint8)
    survive = np.zeros(n, np.uint8)
    for b in rule.birth:
        birth[b] = 1
    for s in rule.survive:
        survive[s] = 1
    return jnp.asarray(birth), jnp.asarray(survive)


def _apply(state: jax.Array, neighbor_counts: jax.Array, rule: Rule) -> jax.Array:
    birth_t, survive_t = _tables(rule)
    c = neighbor_counts.astype(jnp.int32)
    return jnp.where(state == 1, jnp.take(survive_t, c), jnp.take(birth_t, c))


def _require_window_fits(padded_shape, rule: Rule) -> None:
    """Price the shift-add intermediates (the padded count-dtype plane plus
    the separable column-sum plane) through the shared guard — runs at
    trace time, where shapes are static, so an over-cap request raises
    with the knob's name before XLA allocates anything."""
    ph, pw = int(padded_shape[-2]), int(padded_shape[-1])
    item = jnp.dtype(_count_dtype(rule)).itemsize
    planes = [((ph, pw), item), ((ph - 2 * rule.radius, pw), item)]
    guard.require_intermediates_fit(
        sum(guard.plane_bytes(s, i) for s, i in planes),
        what=(
            f"ltl shift-add window sums ({rule}, padded {ph}x{pw}, "
            f"radius {rule.radius})"
        ),
        detail="Shard the board (mesh/cluster) so each tile prices only "
        "its own slice.",
        shapes=planes,
    )


def step_padded_ltl(padded: jax.Array, rule) -> jax.Array:
    """One LtL step on an R-halo-padded tile: (H+2R, W+2R) → (H, W).

    The halo carries the off-tile neighborhood; no wrap happens here — the
    sharded halo path and the toroidal step below both feed it."""
    rule = resolve_rule(rule)
    r = rule.radius
    _require_window_fits(padded.shape, rule)
    alive = (padded == 1).astype(STATE_DTYPE)
    counts = _window_counts(alive, r, rule.neighborhood, _count_dtype(rule))
    interior = padded[r:-r, r:-r]
    # The window sum includes the center; neighbor count excludes it.
    neighbors = counts - alive[r:-r, r:-r].astype(counts.dtype)
    return _apply(interior, neighbors, rule)


def step_ltl(state: jax.Array, rule, engine: str = "shift-add") -> jax.Array:
    """One toroidal LtL step on an (H, W) uint8 board.

    ``engine`` selects the count path: ``"shift-add"`` (the separable VPU
    kernel above) or ``"matmul"`` (the banded matrix-multiply family,
    ``ops/matmul_stencil`` — what ``kernel=matmul`` mounts).  Both apply
    this module's rule tables, so their outputs are bit-identical."""
    rule = resolve_rule(rule)
    if engine == "matmul":
        from akka_game_of_life_tpu.ops import matmul_stencil

        return matmul_stencil.step_matmul(state, rule)
    if engine != "shift-add":
        raise ValueError(f"unknown ltl count engine {engine!r}")
    r = rule.radius
    return step_padded_ltl(jnp.pad(state, r, mode="wrap"), rule)


@functools.lru_cache(maxsize=None)
def ltl_multi_step_fn(
    rule_key, n_steps: int, engine: str = "shift-add"
) -> Callable[[jax.Array], jax.Array]:
    rule = resolve_rule(rule_key)

    @jax.jit
    def _run(state: jax.Array) -> jax.Array:
        def body(s, _):
            return step_ltl(s, rule, engine), None

        out, _ = jax.lax.scan(body, state, None, length=n_steps)
        return out

    return registered_jit(
        "ltl", ("multi_step", rule.name, engine, n_steps), _run,
        # Shift-add visits the (2R+1)-wide window per cell: 2(2R+1) adds
        # via the separable row/col pass.
        cost=lambda state: stencil_cost(
            state.shape[-2], state.shape[-1], n_steps,
            flops_per_cell=4.0 * rule.radius + 4.0,
        ),
    )


def step_padded_ltl_np(padded: np.ndarray, rule) -> np.ndarray:
    """Host-side twin of :func:`step_padded_ltl` via an integral image —
    the numpy oracle for tests and CPU-parity workers."""
    rule = resolve_rule(rule)
    r = rule.radius
    alive = (padded == 1).astype(np.int32)
    h, w = padded.shape[0] - 2 * r, padded.shape[1] - 2 * r
    d = 2 * r + 1
    if rule.neighborhood == "box":
        ii = np.zeros((padded.shape[0] + 1, padded.shape[1] + 1), np.int32)
        ii[1:, 1:] = alive.cumsum(0).cumsum(1)
        window = (
            ii[d : d + h, d : d + w]
            - ii[0:h, d : d + w]
            - ii[d : d + h, 0:w]
            + ii[0:h, 0:w]
        )
    else:
        # Diamond: direct masked sliding sum (independent of the conv path).
        mask = neighborhood_mask(r, rule.neighborhood)
        window = np.zeros((h, w), np.int32)
        for dy in range(d):
            for dx in range(d):
                if mask[dy, dx]:
                    window += alive[dy : dy + h, dx : dx + w]
    interior = padded[r : r + h, r : r + w]
    neighbors = window - alive[r : r + h, r : r + w]
    birth = np.zeros(rule.max_neighbors + 1, np.uint8)
    survive = np.zeros(rule.max_neighbors + 1, np.uint8)
    for b in rule.birth:
        birth[b] = 1
    for s in rule.survive:
        survive[s] = 1
    return np.where(interior == 1, survive[neighbors], birth[neighbors]).astype(
        np.uint8
    )


def step_ltl_np(board: np.ndarray, rule) -> np.ndarray:
    rule = resolve_rule(rule)
    return step_padded_ltl_np(np.pad(board, rule.radius, mode="wrap"), rule)
