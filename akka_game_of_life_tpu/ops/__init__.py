from akka_game_of_life_tpu.ops.rules import Rule, parse_rule  # noqa: F401
from akka_game_of_life_tpu.ops.stencil import (  # noqa: F401
    neighbor_counts,
    step,
    step_fn,
    multi_step,
)
