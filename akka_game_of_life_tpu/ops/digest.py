"""On-device board fingerprints: O(1)-byte state certification.

SURVEY §7 hard part (e): a 65536² board cannot be validated by fetching it
— 512 MiB through a ~21 MB/s tunnel is ~24.5 s per comparison — so the
observation/validation data path must stay on the accelerator (the same
design point as CAX's fully-on-device pipelines and CAT's in-register
verification of packed boards; PAPERS.md).  The digest here is an
order-independent, position-mixing fingerprint every layout can compute
over the SAME mathematical definition, so any two paths holding the same
board produce the same 64-bit value and only ~8 bytes ever cross to the
host:

    key_lane(r, c) = fmix32((r·W + c) XOR seed_lane)        (murmur3 final)
    D_lane        = Σ_cells state(r, c) · key_lane(r, c)     (mod 2³²)
    digest        = (D_hi << 32) | D_lo

Properties that make it a *plane*, not a test helper:

- **order-independent & mergeable**: the sum is over cells, so any
  partition of the board — device shards, cluster tiles, bit planes —
  digests locally (with its *global* cell offsets) and merges by lane-wise
  uint32 addition.  ``psum`` inside ``shard_map`` is exactly that merge
  (:mod:`akka_game_of_life_tpu.parallel.digest`); the TCP cluster merges
  per-tile digests in O(tiles) bytes (``runtime/frontend.py``).
- **position-mixing**: the murmur3 finalizer decorrelates cell index from
  contribution, so transposed/rolled/swapped boards do not collide the way
  a plain popcount (or Σ index) would.
- **per-state weighting**: a cell contributes ``state · key``, so
  Generations/multi-state boards are covered, and the bit-plane form is
  exact by linearity: state = Σ_k 2^k·bit_k ⇒ D = Σ_k (D_plane_k << k).
- **no uint64 anywhere**: two independent 32-bit lanes sidestep JAX's
  default x64-disabled mode while still giving 64 bits of accidental-
  collision resistance; uint32 arithmetic wraps identically in XLA and
  numpy (numpy sums need the explicit ``dtype`` — its default promotes).

Boards beyond 2³² cells wrap the linear index mod 2³² (the flagship
65536² board is exactly the last size with unique indices); wrapping is
deterministic and identical on every path, so cross-path certification is
unaffected — only the collision bound degrades for larger boards.

Device implementations (jnp) and host twins (np) are bit-identical; the
host twins exist for cluster tiles (arbitrary, non-word-aligned shapes)
and checkpoint validation, and process in bounded row blocks so a huge
tile never materializes O(board) of uint32 scratch at once.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# One seed per 32-bit lane; the two lanes together are the 64-bit digest.
LANE_SEEDS = (0x9E3779B9, 0x7F4A7C15)
_M1 = 0x85EBCA6B
_M2 = 0xC2B2AE35

_U = jnp.uint32


def _fmix32(h: jax.Array) -> jax.Array:
    """murmur3's 32-bit finalizer (jnp; uint32 wrap semantics)."""
    h = h ^ (h >> 16)
    h = h * _U(_M1)
    h = h ^ (h >> 13)
    h = h * _U(_M2)
    h = h ^ (h >> 16)
    return h


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    """Host twin of :func:`_fmix32` (mutates its input, which is always a
    scratch copy)."""
    h ^= h >> np.uint32(16)
    h *= np.uint32(_M1)
    h ^= h >> np.uint32(13)
    h *= np.uint32(_M2)
    h ^= h >> np.uint32(16)
    return h


# -- device (jnp) implementations, one per layout ------------------------------


def digest_dense(board: jax.Array, row0=0, col0=0, width: Optional[int] = None):
    """Digest lanes of a dense uint8 board (any state alphabet).

    ``board`` is the (h, w) tile; ``row0``/``col0`` are its global origin
    (traced scalars are fine — the sharded fold passes ``axis_index``
    products) and ``width`` the GLOBAL board width.  Returns (2,) uint32
    ``[lo, hi]``.
    """
    h, w = board.shape[-2], board.shape[-1]
    if width is None:
        width = w
    rows = jax.lax.broadcasted_iota(_U, (h, w), 0) + jnp.asarray(row0, _U)
    cols = jax.lax.broadcasted_iota(_U, (h, w), 1) + jnp.asarray(col0, _U)
    # asarray, not _U(...): ``width`` may be a traced per-board scalar under
    # the serving plane's vmapped fold (digest_dense_batch).
    idx = rows * jnp.asarray(width, _U) + cols
    state = board.astype(_U)
    lanes = [
        jnp.sum(state * _fmix32(idx ^ _U(seed)), dtype=_U)
        for seed in LANE_SEEDS
    ]
    return jnp.stack(lanes)


def digest_packed(words: jax.Array, width: int, row0=0, wordcol0=0):
    """Digest lanes of a bit-packed (h, words) uint32 board (the
    ops/bitpack layout: LSB-first, bit j of word c = cell x = 32c+j).

    Popcount-driven in spirit — only set bits contribute — realized as 32
    unrolled masked accumulations into per-lane ARRAY accumulators with a
    single final reduction each: folding per-bit (64 whole-grid
    reductions) costs ~3x more wall-clock than the elementwise adds XLA
    fuses here (measured: 2.5% vs 7.7% of a 64-step chunk on CPU at
    8192²).  Bit-identical to :func:`digest_dense` of the unpacked board
    — uint32 addition is commutative/associative, so the reduction order
    cannot change the value.
    """
    h, nwords = words.shape[-2], words.shape[-1]
    rows = jax.lax.broadcasted_iota(_U, (h, nwords), 0) + jnp.asarray(row0, _U)
    wcs = jax.lax.broadcasted_iota(_U, (h, nwords), 1) + jnp.asarray(wordcol0, _U)
    base = rows * _U(width) + wcs * _U(32)
    accs = [jnp.zeros((h, nwords), _U), jnp.zeros((h, nwords), _U)]
    for j in range(32):
        idx = base + _U(j)
        bit = (words >> _U(j)) & _U(1)
        for lane, seed in enumerate(LANE_SEEDS):
            accs[lane] = accs[lane] + bit * _fmix32(idx ^ _U(seed))
    return jnp.stack([jnp.sum(acc, dtype=_U) for acc in accs])


def digest_planes(planes: jax.Array, width: int, row0=0, wordcol0=0):
    """Digest lanes of (m, h, words) Generations/WireWorld bit planes
    (ops/bitpack_gen layout, LSB plane first).

    Exact by linearity: state = Σ_k 2^k·bit_k, so the board digest is
    Σ_k (plane_k's binary digest << k), all mod 2³².
    """
    total = jnp.zeros((2,), _U)
    for k in range(planes.shape[0]):
        total = total + (
            digest_packed(planes[k], width, row0, wordcol0) << _U(k)
        )
    return total


def digest_dense_batch(boards: jax.Array, widths) -> jax.Array:
    """Per-board digest lanes of a batched ``[B, H, W]`` uint8 stack —
    the serving plane's certification fold, one ``vmap`` lane per tenant
    board.  Returns ``[B, 2]`` uint32 lanes, board b's row bit-identical
    to ``digest_dense`` of that board alone with global width
    ``widths[b]``.

    Boards of mixed logical shapes ride one stack zero-padded to the
    size-class shape: a padding cell holds state 0 and contributes
    ``0 · key = 0`` to every lane, so padding is invisible to the digest
    and each row certifies exactly the ``[h_b, w_b]`` live region (the
    index stream ``r · widths[b] + c`` over that region is the same one
    the single-board definition walks)."""
    widths = jnp.asarray(widths, _U)
    return jax.vmap(lambda b, w: digest_dense(b, 0, 0, w))(boards, widths)


# -- host (np) twins -----------------------------------------------------------

# Row-block size for the host loops: bounds scratch to O(block · width)
# uint32 temporaries however large the tile is.
_NP_BLOCK_ROWS = 1024


def digest_dense_np(
    arr: np.ndarray,
    origin: Tuple[int, int] = (0, 0),
    width: Optional[int] = None,
) -> np.ndarray:
    """Host twin of :func:`digest_dense`; also the per-tile mergeable form
    for the TCP cluster (tiles have arbitrary, non-word-aligned shapes, so
    the cluster digests cells, never words).  ``origin`` is the tile's
    global (row, col); ``width`` the global board width."""
    arr = np.asarray(arr, dtype=np.uint8)
    h, w = arr.shape
    if width is None:
        width = w
    oy, ox = origin
    cols = (np.arange(w, dtype=np.uint32) + np.uint32(ox))[None, :]
    # Lane accumulators are Python ints masked to 32 bits: a uint32 scalar
    # += would wrap identically but trips numpy's overflow warning.
    lanes = [0, 0]
    for r0 in range(0, h, _NP_BLOCK_ROWS):
        r1 = min(r0 + _NP_BLOCK_ROWS, h)
        rows = (np.arange(r0, r1, dtype=np.uint32) + np.uint32(oy))[:, None]
        idx = rows * np.uint32(width) + cols
        state = arr[r0:r1].astype(np.uint32)
        for lane, seed in enumerate(LANE_SEEDS):
            mixed = _fmix32_np(idx ^ np.uint32(seed))
            mixed *= state
            lanes[lane] = (
                lanes[lane] + int(mixed.sum(dtype=np.uint32))
            ) & 0xFFFFFFFF
    return np.asarray(lanes, dtype=np.uint32)


def digest_packed_np(words: np.ndarray, width: int) -> np.ndarray:
    """Host twin of :func:`digest_packed` ((h, words) uint32 LSB-first)."""
    words = np.asarray(words, dtype=np.uint32)
    h, nwords = words.shape
    wcs = (np.arange(nwords, dtype=np.uint32) * np.uint32(32))[None, :]
    lanes = [0, 0]
    for r0 in range(0, h, _NP_BLOCK_ROWS):
        r1 = min(r0 + _NP_BLOCK_ROWS, h)
        rows = np.arange(r0, r1, dtype=np.uint32)[:, None]
        base = rows * np.uint32(width) + wcs
        block = words[r0:r1]
        for j in range(32):
            idx = base + np.uint32(j)
            bit = (block >> np.uint32(j)) & np.uint32(1)
            for lane, seed in enumerate(LANE_SEEDS):
                mixed = _fmix32_np(idx ^ np.uint32(seed))
                mixed *= bit
                lanes[lane] = (
                    lanes[lane] + int(mixed.sum(dtype=np.uint32))
                ) & 0xFFFFFFFF
    return np.asarray(lanes, dtype=np.uint32)


def digest_planes_np(planes: np.ndarray, width: int) -> np.ndarray:
    """Host twin of :func:`digest_planes` ((m, h, words) uint32)."""
    planes = np.asarray(planes, dtype=np.uint32)
    lanes = np.zeros(2, dtype=np.uint32)
    for k in range(planes.shape[0]):
        lanes += digest_packed_np(planes[k], width) << np.uint32(k)
    return lanes


def digest_payload_np(
    payload: dict, origin: Tuple[int, int], width: int
) -> np.ndarray:
    """Digest lanes of a wire/checkpoint tile payload (``wire.pack_tile``
    form) without the caller materializing the tile — O(tile), one tile at
    a time, never the assembled board."""
    from akka_game_of_life_tpu.runtime.wire import unpack_tile

    return digest_dense_np(unpack_tile(payload), origin, width)


# -- merge / presentation ------------------------------------------------------


def merge_lanes(parts: Iterable) -> np.ndarray:
    """Fold per-part digest lanes into the whole-board lanes: lane-wise
    uint32 sum (the host-side analog of the ``psum`` fold).  Parts are
    (2,)-shaped arrays or (lo, hi) pairs; an empty iterable merges to
    zero lanes (the digest of an empty region)."""
    lo = hi = 0
    for part in parts:
        p = np.asarray(part)
        lo = (lo + int(p[0])) & 0xFFFFFFFF
        hi = (hi + int(p[1])) & 0xFFFFFFFF
    return np.asarray([lo, hi], dtype=np.uint32)


def value(lanes) -> int:
    """The presented 64-bit digest: (hi << 32) | lo, as a Python int."""
    lanes = np.asarray(lanes)
    return (int(lanes[1]) << 32) | int(lanes[0])


def format_digest(v: int) -> str:
    """Canonical text form: 16 hex digits (what metrics lines, checkpoint
    meta, and the ``checkpoints`` CLI print)."""
    return f"{v:016x}"


# -- block-granular lane reuse -------------------------------------------------


class BlockLaneCache:
    """Memoized per-block lane contributions for tiled re-digesting.

    The digest is a sum over cells, so a board tiled into disjoint blocks
    digests as the lane-wise sum of per-block contributions — and a block's
    contribution depends only on (content, origin, board width).  Boards
    that evolve by block substitution (the serve memo plane: most tiles of
    a structured board are static or cycling between a few contents) keep
    re-presenting the same (content, origin) pairs, so their whole-board
    lanes reduce to dict hits plus one :func:`merge_lanes` fold instead of
    an O(board) re-mix every epoch.

    Keys are the caller's canonical content payloads (``ops/macroblock``
    codec bytes) plus origin/width; values are (2,) uint32 lanes.  Bounded
    LRU (``max_entries``) — ~70 bytes/entry of lanes + key overhead, and a
    miss just recomputes, so tightness costs speed, never correctness."""

    def __init__(self, max_entries: int = 1 << 16) -> None:
        from collections import OrderedDict

        self.max_entries = int(max_entries)
        self._entries: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def block_lanes(
        self,
        payload: bytes,
        block: np.ndarray,
        origin: Tuple[int, int],
        width: int,
    ) -> np.ndarray:
        """The block's lane contribution at ``origin`` of a ``width``-wide
        board: cached by (payload, origin, width), computed via
        :func:`digest_dense_np` on miss."""
        key = (payload, origin[0], origin[1], width)
        lanes = self._entries.get(key)
        if lanes is not None:
            self._entries.move_to_end(key)
            self.hits += 1
            return lanes
        self.misses += 1
        lanes = digest_dense_np(block, origin, width)
        self._entries[key] = lanes
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
        return lanes
