"""Canonical macro-cell block codec — the ops half of the serve memo plane.

Hashlife's observation (PAPERS.md, Gosper 1984) is that a 2^k-sided block
of cells *determines* its center 2^(k-1)-sided tile for the next 2^(k-2)
generations, under ANY radius-1 rule: influence travels one cell per
generation, so a center cell at depth ≥ 2^(k-2) from the block edge cannot
see past the edge within that many steps.  That makes the pair

    (rule, block content)  →  center tile after 2^(k-2) steps

a pure function of block *content* — position-free, session-free,
tenant-free — and therefore memoizable across every board that ever
exhibits the same 2^k×2^k neighborhood.  ``serve/memo.py`` builds the
content-addressed cache; this module owns the geometry and the canonical
byte encoding the cache is keyed by:

- :func:`plan` — eligibility + cached toroidal gather/scatter maps for a
  board shape (a board tiles into T-sided result tiles, T = block/2; each
  tile's context is the B-sided block centered on it, extracted with
  toroidal wrap);
- :func:`extract_contexts` — all context blocks of a board in one
  vectorized gather, ``[n_tiles, B, B]``;
- :func:`encode_blocks` / :func:`decode_block` — the canonical payload
  codec (bit-packed for binary rules, raw C-order bytes for multi-state
  Generations rules; byte-for-byte deterministic in both directions);
- :func:`block_key` — the cheap content hash (crc32) the cache buckets
  by.  crc32 is 32 bits on purpose: collisions are *expected* at scale,
  and the cache resolves them by full payload compare (never by trusting
  the hash), so the hash only has to be fast.

Correctness of the toroidal shortcut: the device path steps the extracted
B×B block *toroidally* (reusing the serve batch kernel).  Wrap-corrupted
values enter at the block edge and travel inward one cell per step, so
after S = B/4 steps they reach depth < S — and every center-tile cell sits
at depth ≥ S.  When the board itself is narrower than the block (side
T = B/2, the smallest eligible side), the wrapped extraction is exactly
T-periodic, the toroidal step preserves that periodicity, and the periodic
dynamics quotient to the true T-torus dynamics — so the center is exact in
every eligible geometry.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "MacroPlan",
    "block_key",
    "decode_block",
    "encode_blocks",
    "extract_contexts",
    "plan",
]

# Smallest supported block: 16 → 8-sided tiles advancing 4 epochs per
# macro-step.  Below that the halo (B/4) is under the practical minimum
# for the gather layout and the memo quantum stops paying for its hashing.
MIN_BLOCK = 16


@dataclass(frozen=True, eq=False)
class MacroPlan:
    """Macro-step geometry for one (height, width, block) combination.

    ``rows``/``cols`` are the wrapped context gather maps: tile (i, j)'s
    B-sided context block is ``board[rows[i]][:, cols[j]]`` — rows[i][k] =
    (i·T − S + k) mod height.  Extraction for ALL tiles happens in one
    fancy-index gather (:func:`extract_contexts`).
    """

    height: int
    width: int
    block: int          # context block side B (power of two)
    tile: int           # result tile side T = B // 2
    steps: int          # epochs one macro-step advances: S = B // 4
    n_tr: int           # tile rows  = height // T
    n_tc: int           # tile cols  = width  // T
    rows: np.ndarray = field(repr=False)    # [n_tr, B] int32 wrapped rows
    cols: np.ndarray = field(repr=False)    # [n_tc, B] int32 wrapped cols

    @property
    def n_tiles(self) -> int:
        return self.n_tr * self.n_tc

    def origins(self) -> List[Tuple[int, int]]:
        """Tile origins in board coordinates, row-major tile order (the
        order :func:`extract_contexts` emits blocks in)."""
        t = self.tile
        return [
            (i * t, j * t) for i in range(self.n_tr) for j in range(self.n_tc)
        ]

    def assemble(self, centers: np.ndarray) -> np.ndarray:
        """Inverse of the tiling: ``[n_tiles, T, T]`` center results →
        the (height, width) board they compose, row-major tile order."""
        t = self.tile
        return (
            centers.reshape(self.n_tr, self.n_tc, t, t)
            .transpose(0, 2, 1, 3)
            .reshape(self.height, self.width)
        )


# plan() is pure geometry keyed by three small ints — memoized because the
# serve ticker asks for it on every memo tick of every session.
_PLANS: Dict[Tuple[int, int, int], Optional[MacroPlan]] = {}


def plan(height: int, width: int, block: int) -> Optional[MacroPlan]:
    """The macro-step plan for a board shape, or None when the shape is
    ineligible (sides must be positive multiples of the tile side T =
    block/2 so the T-tiling is exact; everything else degrades to the
    dense path, never to a wrong answer)."""
    key = (height, width, block)
    got = _PLANS.get(key, False)
    if got is not False:
        return got
    p: Optional[MacroPlan] = None
    t = block // 2
    s = block // 4
    if (
        block >= MIN_BLOCK
        and block & (block - 1) == 0
        and height > 0
        and width > 0
        and height % t == 0
        and width % t == 0
    ):
        span = np.arange(block, dtype=np.int64) - s
        rows = np.stack(
            [(i * t + span) % height for i in range(height // t)]
        ).astype(np.int32)
        cols = np.stack(
            [(j * t + span) % width for j in range(width // t)]
        ).astype(np.int32)
        p = MacroPlan(
            height=height, width=width, block=block, tile=t, steps=s,
            n_tr=height // t, n_tc=width // t, rows=rows, cols=cols,
        )
    _PLANS[key] = p
    return p


def extract_contexts(board: np.ndarray, p: MacroPlan) -> np.ndarray:
    """Every tile's toroidal context block in one gather:
    ``[n_tiles, B, B]`` uint8, row-major tile order."""
    # board[rows] → [n_tr, B, W]; [..., cols] → [n_tr, B, n_tc, B].
    ctx = board[p.rows][:, :, p.cols]
    return (
        ctx.transpose(0, 2, 1, 3).reshape(p.n_tiles, p.block, p.block)
    )


def encode_blocks(blocks: np.ndarray, states: int) -> List[bytes]:
    """Canonical payloads for a ``[n, side, side]`` uint8 block stack.

    Binary rules (states == 2) bit-pack (8 cells/byte, C-order, zero-padded
    tail — ``np.packbits`` semantics); multi-state rules ship raw C-order
    bytes (cell values up to states−1 don't fit a bit).  The encoding is a
    bijection on valid blocks, so payload equality ⟺ block equality — the
    property the cache's collision handling rests on."""
    n = blocks.shape[0]
    if states == 2:
        packed = np.packbits(blocks.reshape(n, -1), axis=1)
        return [packed[i].tobytes() for i in range(n)]
    return [blocks[i].tobytes() for i in range(n)]


def decode_block(payload: bytes, side: int, states: int) -> np.ndarray:
    """Inverse of :func:`encode_blocks` for one payload → (side, side)
    uint8 block."""
    if states == 2:
        flat = np.unpackbits(
            np.frombuffer(payload, dtype=np.uint8), count=side * side
        )
        return flat.reshape(side, side)
    return (
        np.frombuffer(payload, dtype=np.uint8)
        .reshape(side, side)
        .copy()
    )


def block_key(payload: bytes) -> int:
    """The bucket hash: crc32 of the canonical payload.  Weak on purpose
    (fast beats wide here); the cache compares full payloads within a
    bucket, so a collision costs a memcmp, never a wrong answer."""
    return zlib.crc32(payload)
