"""Pallas TPU kernel for Larger-than-Life: VMEM-blocked shift-add counts.

The XLA LtL path (:mod:`akka_game_of_life_tpu.ops.ltl`) materializes its
separable count passes in HBM between fusions — the same scheduling toll
the binary SWAR kernel paid before its Mosaic sweep (BASELINE.md: 2.05×10¹¹
→ 1.82×10¹² at 65536²).  Here one grid step loads a ``block_rows + 2R``
row slab into VMEM, wraps the columns in-register, runs the column then
row slice-sum passes entirely in VMEM, applies the rule, and writes the
central ``block_rows`` back — HBM sees one uint8 read and one write of the
board per step.  At ~2(2R+1) bf16 adds/cell the kernel is compute-bound,
so no temporal blocking (extra halo recompute would cost more than the
HBM traffic it saves — the measured k=16 lesson from the binary sweep).

The birth/survive sets are applied as range compares, not a table gather:
LtL rules are written as count *ranges* (``R5,B15-22,S15-25``), and an
arbitrary set decomposes into a handful of contiguous runs — each run is
two compares, which Mosaic vectorizes trivially where a gather would not
lower.  Counts stay exact in bf16 to 256 and f32 beyond, same dtype rule
as the XLA path.

Torus wraps: rows through the halo BlockSpec ``index_map`` modulo (as in
:mod:`akka_game_of_life_tpu.ops.pallas_stencil`), columns by an
in-kernel concat of the east/west edges (a (rows, R) VMEM copy).

Box neighborhoods only: the diamond's per-row widths defeat the separable
two-pass form; it stays on the XLA cumsum-difference path.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from akka_game_of_life_tpu.ops.ltl import _count_dtype
from akka_game_of_life_tpu.ops.pallas_stencil import _round_up8
from akka_game_of_life_tpu.ops.rules import resolve_rule

DEFAULT_BLOCK_ROWS = 128


def _ranges(counts) -> List[Tuple[int, int]]:
    """A sorted count set as inclusive (lo, hi) runs: {3,4,5,9} →
    [(3,5), (9,9)]."""
    runs: List[Tuple[int, int]] = []
    for n in sorted(counts):
        if runs and n == runs[-1][1] + 1:
            runs[-1] = (runs[-1][0], n)
        else:
            runs.append((n, n))
    return runs


def _in_ranges(c: jax.Array, runs: List[Tuple[int, int]]) -> jax.Array:
    hit = None
    for lo, hi in runs:
        t = (c >= lo) & (c <= hi)
        hit = t if hit is None else hit | t
    return hit if hit is not None else jnp.zeros(c.shape, jnp.bool_)


def ltl_sweep_fn(
    rule,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """One Pallas step advancing a (H, W) uint8 LtL torus by one
    generation.  Requires ``H % block_rows == 0`` and a box neighborhood."""
    rule = resolve_rule(rule)
    if rule.kind != "ltl" or rule.neighborhood != "box":
        raise ValueError(
            f"pallas LtL kernel supports kind='ltl' box neighborhoods, got {rule}"
        )
    r = rule.radius
    d = 2 * r + 1
    b = block_rows
    hb = _round_up8(r)  # sublane-aligned halo blocks; last/first r rows used
    if b % hb:
        raise ValueError(
            f"block_rows={b} must be a multiple of {hb} (radius {r} rounded "
            f"up to the 8-row sublane tile)"
        )
    dtype = _count_dtype(rule)
    birth_runs = _ranges(rule.birth)
    survive_runs = _ranges(rule.survive)

    def kernel(north_ref, center_ref, south_ref, out_ref):
        ext = jnp.concatenate(
            [north_ref[hb - r :], center_ref[...], south_ref[:r]], axis=0
        )  # (b + 2r, W)
        # Column torus wrap in-register.
        ext = jnp.concatenate([ext[:, -r:], ext, ext[:, :r]], axis=1)
        alive = (ext == 1).astype(dtype)  # (b+2r, W+2r)
        h_out, w_out = b, ext.shape[1] - 2 * r
        col = alive[0:h_out]
        for dy in range(1, d):
            col = col + alive[dy : dy + h_out]  # (b, W+2r)
        counts = col[:, 0:w_out]
        for dx in range(1, d):
            counts = counts + col[:, dx : dx + w_out]  # (b, W)
        center = ext[r : r + h_out, r : r + w_out]
        alive_c = center == 1
        neighbors = counts - alive_c.astype(dtype)
        next_alive = jnp.where(
            alive_c,
            _in_ranges(neighbors, survive_runs),
            _in_ranges(neighbors, birth_runs),
        )
        out_ref[...] = next_alive.astype(ext.dtype)

    def sweep(x: jax.Array) -> jax.Array:
        h, w = x.shape
        if h % b:
            raise ValueError(f"grid height {h} not a multiple of block_rows={b}")
        halo_blocks = h // hb

        grid_spec = pl.GridSpec(
            grid=(h // b,),
            in_specs=[
                pl.BlockSpec(
                    (hb, w),
                    lambda i: ((i * (b // hb) - 1) % halo_blocks, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec((b, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec(
                    (hb, w),
                    lambda i: (((i + 1) * (b // hb)) % halo_blocks, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (b, w), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        )
        compiler_params = None
        if vmem_limit_bytes is not None and not interpret:
            compiler_params = pltpu.CompilerParams(
                vmem_limit_bytes=vmem_limit_bytes
            )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid_spec=grid_spec,
            interpret=interpret,
            compiler_params=compiler_params,
        )(x, x, x)

    return sweep


@functools.lru_cache(maxsize=None)
def ltl_pallas_multi_step_fn(
    rule_key,
    n_steps: int,
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    interpret: bool = False,
    vmem_limit_bytes: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Jitted n-step LtL advance from single-generation Pallas sweeps."""
    rule = resolve_rule(rule_key)
    sweep = ltl_sweep_fn(
        rule,
        block_rows=block_rows,
        interpret=interpret,
        vmem_limit_bytes=vmem_limit_bytes,
    )

    @jax.jit
    def run(x: jax.Array) -> jax.Array:
        def body(s, _):
            return sweep(s), None

        out, _ = jax.lax.scan(body, x, None, length=n_steps)
        return out

    from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost

    return registered_jit(
        "pallas_ltl", ("multi_step", rule.name, n_steps, block_rows), run,
        cost=lambda x: stencil_cost(
            x.shape[-2], x.shape[-1], n_steps,
            flops_per_cell=4.0 * rule.radius + 4.0,
        ),
    )
