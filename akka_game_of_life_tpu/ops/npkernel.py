"""Plain-numpy CA kernels for host-side tile stepping.

The distributed control plane steps coarse tiles inside worker processes.  A
worker whose shard lives on a TPU uses the jitted stencil
(:mod:`akka_game_of_life_tpu.ops.stencil`); a CPU-only worker (the parity
configuration, BASELINE.json config 1) uses these numpy twins — identical
semantics, no device runtime required.  Both consume the same halo-padded
tile layout, so the engines are swappable per worker (the role-config
pluggability the reference gets from its actor protocol).
"""

from __future__ import annotations

import numpy as np

from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule


def _apply_rule_np(state: np.ndarray, counts: np.ndarray, rule: Rule) -> np.ndarray:
    c = counts.astype(np.uint32)
    birth = ((np.uint32(rule.birth_mask) >> c) & 1).astype(np.uint8)
    if not rule.is_totalistic:  # wireworld: see ops/stencil.apply_rule
        # (survive plane skipped — unused by this kind, and unlike the jax
        # twin there is no compiler to dead-code-eliminate it.)
        return np.where(
            state == 1,
            np.uint8(2),
            np.where(
                state == 2,
                np.uint8(3),
                np.where((state == 3) & (birth == 1), np.uint8(1), state),
            ),
        ).astype(np.uint8)
    survive = ((np.uint32(rule.survive_mask) >> c) & 1).astype(np.uint8)
    if rule.is_binary:
        return np.where(state == 1, survive, birth).astype(np.uint8)
    decayed = np.where(state + 1 < rule.states, state + 1, 0).astype(np.uint8)
    live_next = np.where(survive == 1, 1, 2).astype(np.uint8)
    return np.where(
        state == 0, birth, np.where(state == 1, live_next, decayed)
    ).astype(np.uint8)


def neighbor_counts_padded_np(padded_alive: np.ndarray) -> np.ndarray:
    h, w = padded_alive.shape[0] - 2, padded_alive.shape[1] - 2
    acc = np.zeros((h, w), dtype=np.uint8)
    for dy in (0, 1, 2):
        for dx in (0, 1, 2):
            if (dy, dx) == (1, 1):
                continue
            acc += padded_alive[dy : dy + h, dx : dx + w]
    return acc


def step_padded_np(padded: np.ndarray, rule) -> np.ndarray:
    """One step on a radius-deep halo-padded tile: (h+2R, w+2R) → (h, w)."""
    rule = resolve_rule(rule)
    if rule.kind == "ltl":
        from akka_game_of_life_tpu.ops.ltl import step_padded_ltl_np

        return step_padded_ltl_np(padded, rule)
    alive = (padded == 1).astype(np.uint8)
    counts = neighbor_counts_padded_np(alive)
    return _apply_rule_np(padded[1:-1, 1:-1], counts, rule)


def step_np(board: np.ndarray, rule) -> np.ndarray:
    """One toroidal step on a full board (numpy oracle / CPU engine)."""
    rule = resolve_rule(rule)
    return step_padded_np(np.pad(board, rule.radius, mode="wrap"), rule)
