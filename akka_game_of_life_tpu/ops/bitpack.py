"""Bit-packed SWAR stencil: 32 cells per uint32 lane.

The roll-based uint8 kernel is HBM-bandwidth-bound at ~1 byte/cell/pass.
Packing 1 cell/bit cuts traffic 8x and turns the Moore count into bitwise
carry-save adders on the VPU — the classic SWAR Life algorithm, laid out for
XLA: everything is elementwise int32 ops + three row/word rolls, which XLA
fuses into one pass over the packed grid.

Layout: grid (H, W) uint8 → packed (H, W/32) uint32, LSB-first within a word
(bit i of word k = cell x = 32k+i).  Horizontal neighbor planes cross word
boundaries via (x << 1) | (prev_word >> 31) and its mirror; vertical
neighbors are row rolls; the torus wraps for free on both axes.

Binary (2-state) rules only — Generations CA stays on the uint8 path.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.obs.programs import registered_jit
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule

LANE_BITS = 32
_U = jnp.uint32


def require_packed_support(rule: Rule) -> None:
    """The SWAR kernels encode binary radius-1 outer-totalistic semantics;
    everything else (Generations planes, wireworld, radius-R ltl) has its
    own path.  ltl rules ARE binary, so an is_binary check alone would let
    them through and silently compute radius-1 — hence the shared guard."""
    if not (rule.is_binary and rule.is_totalistic):
        raise ValueError(
            f"bit-packed kernel supports binary radius-1 totalistic rules "
            f"only, got {rule}"
        )


def pack(grid) -> jax.Array:
    """(H, W) 0/1 uint8 → (H, W/32) uint32, LSB-first.

    Stays in uint8 until a final word-level bitcast so the peak intermediate
    is 1 byte/cell — a 65536² board packs within ~4 GiB of scratch instead of
    the 17 GiB a uint32 (H, W/32, 32) lane tensor would need.
    """
    grid = jnp.asarray(grid, dtype=jnp.uint8)
    h, w = grid.shape
    if w % LANE_BITS:
        raise ValueError(f"width {w} not a multiple of {LANE_BITS}")
    packed_bytes = jnp.packbits(grid, axis=-1, bitorder="little")
    # (H, W/8) LSB-first bytes → uint32 words (TPU/x86 are little-endian, so
    # byte 0 of the word is bits 0-7 — matching the LSB-first cell layout).
    return jax.lax.bitcast_convert_type(
        packed_bytes.reshape(h, w // LANE_BITS, LANE_BITS // 8), jnp.uint32
    )


def unpack(packed: jax.Array) -> jax.Array:
    """(H, W/32) uint32 → (H, W) uint8.  1 byte/cell peak (see ``pack``)."""
    h, words = packed.shape
    packed_bytes = jax.lax.bitcast_convert_type(packed, jnp.uint8)  # (H, W/32, 4)
    return jnp.unpackbits(
        packed_bytes.reshape(h, words * (LANE_BITS // 8)), axis=-1, bitorder="little"
    )


def _hshift_west(x: jax.Array) -> jax.Array:
    """Plane of west neighbors: bit i ← cell (x-1), wrapping across words
    and the torus edge."""
    prev_word = jnp.roll(x, 1, axis=1)
    return (x << 1) | (prev_word >> (LANE_BITS - 1))


def _hshift_east(x: jax.Array) -> jax.Array:
    next_word = jnp.roll(x, -1, axis=1)
    return (x >> 1) | (next_word << (LANE_BITS - 1))


def _row_triple_sum(x: jax.Array):
    """Per-row horizontal 3-cell sums *including the center cell*.

    Returns bit planes ``(s, c)`` with per-bit count ``west+center+east =
    s + 2c`` (a full adder).  Computed ONCE per row and reused as the
    north/center/south contribution of three different output rows — the
    classic shared-row-sum Life optimization that nearly halves the VPU op
    count versus summing eight neighbor planes per output row.
    """
    w = _hshift_west(x)
    e = _hshift_east(x)
    xw = x ^ w
    return xw ^ e, (x & w) | (e & xw)


def _count_bits(sN, cN, sC, cC, sS, cS):
    """Assemble ``count = (sN+sC+sS) + 2*(cN+cC+cS)`` — the 9-cell Moore sum
    including the center, range 0..9 — into bit planes (b3, b2, b1, b0)."""
    sNC = sN ^ sC
    b0 = sNC ^ sS  # weight-1 sum bit
    p1 = (sN & sC) | (sS & sNC)  # weight-2 carry of the s's
    cNC = cN ^ cC
    q0 = cNC ^ cS  # weight-2 sum of the c's
    q1 = (cN & cC) | (cS & cNC)  # weight-4 carry of the c's
    b1 = p1 ^ q0
    r2 = p1 & q0
    b2 = q1 ^ r2
    b3 = q1 & r2
    return b3, b2, b1, b0


def count_eq_fn(b3, b2, b1, b0):
    """A predicate plane factory: ``eq(n)`` = bits where the 4-bit count
    equals n (0..9)."""
    nb3, nb2, nb1, nb0 = ~b3, ~b2, ~b1, ~b0

    def eq(n: int) -> jax.Array:
        t = b3 if n & 8 else nb3
        t = t & (b2 if n & 4 else nb2)
        t = t & (b1 if n & 2 else nb1)
        return t & (b0 if n & 1 else nb0)

    return eq


def _combine_rows(x, sN, cN, sC, cC, sS, cS, rule: Rule) -> jax.Array:
    """Next state from three rows' (s, c) triple-sum planes.

    Because the center is included in the count, survive thresholds shift by
    +1: for a B/S rule, next = (~x & [count ∈ B]) | (x & [count-1 ∈ S]).
    Counts in B ∩ (S+1) make the cell alive *regardless* of x (count == n
    means n neighbors when dead, n-1 when alive), so those predicates skip
    the x masking entirely — for Conway the combine collapses to
    ``eq(3) | (x & eq(4))``, saving a ~x/&/| chain the compiler's CSE
    cannot fold on its own.
    """
    eq = count_eq_fn(*_count_bits(sN, cN, sC, cC, sS, cS))

    def union(ns):
        acc = None
        for n in sorted(ns):
            acc = eq(n) if acc is None else acc | eq(n)
        return acc

    survive_counts = {n + 1 for n in rule.survive}  # count includes the center
    always = rule.birth & survive_counts
    terms = [union(always)]
    birth = union(rule.birth - always)
    if birth is not None:
        terms.append(~x & birth)
    survive = union(survive_counts - always)
    if survive is not None:
        terms.append(x & survive)
    terms = [t for t in terms if t is not None]
    return functools.reduce(jnp.bitwise_or, terms) if terms else jnp.zeros_like(x)


def step_padded_rows(padded: jax.Array, rule) -> jax.Array:
    """One packed step on a row-padded slab: (h+2, words) with one halo row
    top and bottom → (h, words).  Row sums are computed once per slab row and
    shared across the three output rows each feeds (see
    :func:`_row_triple_sum`).  Used by the row-sharded halo path."""
    rule = resolve_rule(rule)
    s, c = _row_triple_sum(padded)
    return _combine_rows(
        padded[1:-1], s[:-2], c[:-2], s[1:-1], c[1:-1], s[2:], c[2:], rule
    )


def step_packed(x: jax.Array, rule) -> jax.Array:
    """One toroidal step on a packed (H, W/32) uint32 grid."""
    rule = resolve_rule(rule)
    require_packed_support(rule)
    s, c = _row_triple_sum(x)
    return _combine_rows(
        x,
        jnp.roll(s, 1, axis=0),
        jnp.roll(c, 1, axis=0),
        s,
        c,
        jnp.roll(s, -1, axis=0),
        jnp.roll(c, -1, axis=0),
        rule,
    )


def _packed_cost(x, steps: int) -> dict:
    """Plan-priced per-call cost of a packed-word kernel: 1 bit/cell on
    the wire, ~2 word-ops per cell-update in the adder tree."""
    cells = float(x.size) * x.dtype.itemsize * 8 * steps
    return {
        "cells": cells,
        "bytes": 2.0 * x.size * x.dtype.itemsize * steps,
        "flops": 2.0 * cells,
    }


@functools.lru_cache(maxsize=None)
def packed_step_fn(rule_key: Rule) -> Callable[[jax.Array], jax.Array]:
    rule = resolve_rule(rule_key)

    @jax.jit
    def _step(x: jax.Array) -> jax.Array:
        return step_packed(x, rule)

    return registered_jit(
        "bitpack", ("step", rule.name), _step,
        cost=lambda x: _packed_cost(x, 1),
    )


@functools.lru_cache(maxsize=None)
def packed_multi_step_fn(rule_key: Rule, n_steps: int) -> Callable[[jax.Array], jax.Array]:
    rule = resolve_rule(rule_key)

    @jax.jit
    def _run(x: jax.Array) -> jax.Array:
        def body(s, _):
            return step_packed(s, rule), None

        out, _ = jax.lax.scan(body, x, None, length=n_steps)
        return out

    return registered_jit(
        "bitpack", ("multi_step", rule.name, n_steps), _run,
        cost=lambda x: _packed_cost(x, n_steps),
    )


def pack_np(grid: np.ndarray) -> np.ndarray:
    """Host-side packer (for checkpoints / wire transfers).

    Peak scratch is board/8 bytes (the packbits output viewed as words) —
    a 65536² board packs within ~512 MiB, not the 16 GiB a uint32 lane
    tensor would cost."""
    h, w = grid.shape
    if w % LANE_BITS:
        raise ValueError(f"width {w} not a multiple of {LANE_BITS}")
    packed_bytes = np.packbits(
        np.asarray(grid, dtype=np.uint8), axis=-1, bitorder="little"
    )
    # 4 consecutive LSB-first bytes little-endian-viewed = one LSB-first word.
    return (
        np.ascontiguousarray(packed_bytes)
        .reshape(h, (w // LANE_BITS) * 4)
        .view("<u4")
    )


def unpack_np(words: np.ndarray) -> np.ndarray:
    """Host-side unpacker: (H, W/32) uint32 LSB-first words → (H, W) uint8."""
    h, w32 = words.shape
    # Little-endian byte view matches the LSB-first cell layout (see pack()).
    packed_bytes = np.ascontiguousarray(words.astype("<u4")).view(np.uint8)
    return np.unpackbits(
        packed_bytes.reshape(h, w32 * 4), axis=-1, bitorder="little"
    )


def population_rows(x: jax.Array) -> jax.Array:
    """Device-side per-row population of a packed board: (H, W/32) uint32 →
    (H,) uint32 row counts.  Row sums cannot overflow (a row holds at most
    32·W/32 = W ≤ 2³²−1 cells); callers sum the rows on host in int64 so a
    65536² board's population (up to 2³²) is exact — and only the (H,)
    vector ever crosses to the host, never the board.  Unjitted: callers
    wrap it to suit their sharding (jit, or auto_axes on a mesh)."""
    return jnp.sum(jnp.bitwise_count(x).astype(jnp.uint32), axis=1)


def sample_packed_core(
    sy: int, sx: int, width: int
) -> Callable[[jax.Array], jax.Array]:
    """Device-side strided probe of a packed board: bit (x·sx) of every
    sy-th row, as a small uint8 view — the render sample for boards too big
    to ship (a 65536² frame never leaves the device).  Unjitted core, like
    :func:`population_rows`."""
    xs = np.arange(0, width, sx)
    word_idx = jnp.asarray(xs // LANE_BITS)
    bit_idx = jnp.asarray((xs % LANE_BITS).astype(np.uint32))

    def _sample(x: jax.Array) -> jax.Array:
        rows = x[::sy]
        return ((rows[:, word_idx] >> bit_idx) & 1).astype(jnp.uint8)

    return _sample
