"""Bit-packed SWAR stencil: 32 cells per uint32 lane.

The roll-based uint8 kernel is HBM-bandwidth-bound at ~1 byte/cell/pass.
Packing 1 cell/bit cuts traffic 8x and turns the Moore count into bitwise
carry-save adders on the VPU — the classic SWAR Life algorithm, laid out for
XLA: everything is elementwise int32 ops + three row/word rolls, which XLA
fuses into one pass over the packed grid.

Layout: grid (H, W) uint8 → packed (H, W/32) uint32, LSB-first within a word
(bit i of word k = cell x = 32k+i).  Horizontal neighbor planes cross word
boundaries via (x << 1) | (prev_word >> 31) and its mirror; vertical
neighbors are row rolls; the torus wraps for free on both axes.

Binary (2-state) rules only — Generations CA stays on the uint8 path.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule

LANE_BITS = 32
_U = jnp.uint32


def pack(grid) -> jax.Array:
    """(H, W) 0/1 uint8 → (H, W/32) uint32, LSB-first.

    Stays in uint8 until a final word-level bitcast so the peak intermediate
    is 1 byte/cell — a 65536² board packs within ~4 GiB of scratch instead of
    the 17 GiB a uint32 (H, W/32, 32) lane tensor would need.
    """
    grid = jnp.asarray(grid, dtype=jnp.uint8)
    h, w = grid.shape
    if w % LANE_BITS:
        raise ValueError(f"width {w} not a multiple of {LANE_BITS}")
    packed_bytes = jnp.packbits(grid, axis=-1, bitorder="little")
    # (H, W/8) LSB-first bytes → uint32 words (TPU/x86 are little-endian, so
    # byte 0 of the word is bits 0-7 — matching the LSB-first cell layout).
    return jax.lax.bitcast_convert_type(
        packed_bytes.reshape(h, w // LANE_BITS, LANE_BITS // 8), jnp.uint32
    )


def unpack(packed: jax.Array) -> jax.Array:
    """(H, W/32) uint32 → (H, W) uint8.  1 byte/cell peak (see ``pack``)."""
    h, words = packed.shape
    packed_bytes = jax.lax.bitcast_convert_type(packed, jnp.uint8)  # (H, W/32, 4)
    return jnp.unpackbits(
        packed_bytes.reshape(h, words * (LANE_BITS // 8)), axis=-1, bitorder="little"
    )


def _hshift_west(x: jax.Array) -> jax.Array:
    """Plane of west neighbors: bit i ← cell (x-1), wrapping across words
    and the torus edge."""
    prev_word = jnp.roll(x, 1, axis=1)
    return (x << 1) | (prev_word >> (LANE_BITS - 1))


def _hshift_east(x: jax.Array) -> jax.Array:
    next_word = jnp.roll(x, -1, axis=1)
    return (x >> 1) | (next_word << (LANE_BITS - 1))


def _popcount_planes(planes):
    """Sum eight 1-bit planes into 4 bit-plane count bits (b3..b0) with
    carry-save adders — ~30 bitwise ops, no integer adds."""
    a0, a1, a2, a3, a4, a5, a6, a7 = planes
    # stage 1: pairwise half-adders (weight-1 sums, weight-2 carries)
    s0, c0 = a0 ^ a1, a0 & a1
    s1, c1 = a2 ^ a3, a2 & a3
    s2, c2 = a4 ^ a5, a4 & a5
    s3, c3 = a6 ^ a7, a6 & a7
    # weight-1: s0+s1+s2+s3
    t0, u0 = s0 ^ s1, s0 & s1
    t1, u1 = s2 ^ s3, s2 & s3
    b0 = t0 ^ t1
    v0 = t0 & t1
    # weight-2 inputs: c0..c3, u0, u1, v0  (7 values)
    p0, q0 = c0 ^ c1, c0 & c1
    p1, q1 = c2 ^ c3, c2 & c3
    w0 = u0 ^ u1 ^ v0
    w1 = (u0 & u1) | (u0 & v0) | (u1 & v0)  # weight-4 carry
    r0, r1 = p0 ^ p1, p0 & p1
    b1 = r0 ^ w0
    r2 = r0 & w0
    # weight-4 inputs: q0, q1, r1, r2, w1  (5 values)
    e0, f0 = q0 ^ q1, q0 & q1
    e1, f1 = r1 ^ r2, r1 & r2
    g0 = e0 ^ e1
    g1 = e0 & e1
    b2 = g0 ^ w1
    g2 = g0 & w1
    # weight-8: f0, f1, g1, g2 — at most one can be set (count <= 8)
    b3 = f0 | f1 | g1 | g2
    return b3, b2, b1, b0


def step_planes(x: jax.Array, north: jax.Array, south: jax.Array, rule: Rule) -> jax.Array:
    """One packed step given explicit north/south row planes (same-shape
    vertical shifts of ``x``); horizontal carries are handled internally via
    word rolls.  Shared by the toroidal single-device step (planes = row
    rolls) and the row-sharded step (planes = halo slices)."""
    planes = (
        _hshift_west(north),
        north,
        _hshift_east(north),
        _hshift_west(x),
        _hshift_east(x),
        _hshift_west(south),
        south,
        _hshift_east(south),
    )
    b3, b2, b1, b0 = _popcount_planes(planes)
    nb3, nb2, nb1, nb0 = ~b3, ~b2, ~b1, ~b0

    def eq(n: int) -> jax.Array:
        t = b3 if n & 8 else nb3
        t = t & (b2 if n & 4 else nb2)
        t = t & (b1 if n & 2 else nb1)
        return t & (b0 if n & 1 else nb0)

    birth = jnp.uint32(0)
    for n in rule.birth:
        birth = birth | eq(n)
    survive = jnp.uint32(0)
    for n in rule.survive:
        survive = survive | eq(n)
    return (~x & birth) | (x & survive)


def step_packed(x: jax.Array, rule) -> jax.Array:
    """One toroidal step on a packed (H, W/32) uint32 grid."""
    rule = resolve_rule(rule)
    if not rule.is_binary:
        raise ValueError("bit-packed kernel supports binary rules only")
    return step_planes(x, jnp.roll(x, 1, axis=0), jnp.roll(x, -1, axis=0), rule)


@functools.lru_cache(maxsize=None)
def packed_step_fn(rule_key: Rule) -> Callable[[jax.Array], jax.Array]:
    rule = resolve_rule(rule_key)

    @jax.jit
    def _step(x: jax.Array) -> jax.Array:
        return step_packed(x, rule)

    return _step


@functools.lru_cache(maxsize=None)
def packed_multi_step_fn(rule_key: Rule, n_steps: int) -> Callable[[jax.Array], jax.Array]:
    rule = resolve_rule(rule_key)

    @jax.jit
    def _run(x: jax.Array) -> jax.Array:
        def body(s, _):
            return step_packed(s, rule), None

        out, _ = jax.lax.scan(body, x, None, length=n_steps)
        return out

    return _run


def pack_np(grid: np.ndarray) -> np.ndarray:
    """Host-side packer (for checkpoints / wire transfers)."""
    h, w = grid.shape
    if w % LANE_BITS:
        raise ValueError(f"width {w} not a multiple of {LANE_BITS}")
    lanes = grid.astype(np.uint32).reshape(h, w // LANE_BITS, LANE_BITS)
    weights = (np.uint32(1) << np.arange(LANE_BITS, dtype=np.uint32))
    return (lanes * weights).sum(axis=-1, dtype=np.uint32)
