"""MXU stencil family: neighbor counting as banded matrix multiplies.

CAT ("Cellular Automata on Tensor cores", PAPERS.md) observes that the
Moore window sum factors into two banded matrix products

    W = A_R · S · A_Rᵀ

where ``A_R`` is the ±R-band circulant (ones on diagonals −R..R, wrapping
at the torus seam) — the shape tensor units execute at int8/bf16 rates
while every other kernel in this repo counts neighbors with VPU
shift-adds.  The same factorization gives radius-R Larger-than-Life for
free (band of width 2R+1, where ``ops/ltl.py`` pays 2(2R+1) separable
shift-add passes), and it is the substrate the continuous-CA roadmap item
compiles onto: radius-R convolution *is* this banded matmul (CAX).

The band is evaluated **block-diagonally**, never as a dense (n, n)
operand: each row/column tile multiplies a (K, K+2R) slab of ``A_R``
against a contiguous slice of the board, with the torus wrap folded into
the edge tiles' operands — O(K) MACs/cell instead of O(n), with K sized
so every product is one large rank-2 GEMM (``jnp.dot``), the MXU's native
diet.  The recorded LtL OOM lesson applies doubly here (a full-size band
matrix at 65536² is 16 GiB before the first multiply), so every plan is
priced through :mod:`ops/guard` at trace time — refuse loudly, never
allocate-and-die.

Three dtype lanes, all producing **exactly** the same integer counts:

- ``int8``: int8 operands accumulating to int32 via
  ``preferred_element_type`` — counts never overflow (row sums ≤ 2R+1 ≤
  21 fit int8; window sums ≤ (2R+1)² ≤ 441 fit int32 trivially).  The MXU
  lane; default on TPU.
- ``bf16``: bf16 operands, f32 accumulation.  Exact because every operand
  value ≤ 2R+1 ≤ 21 is bf16-representable and f32 accumulation of ≤ 2²⁴
  integers is exact; A/B'd for accuracy-equivalence against int32 in
  ``tests/test_matmul_stencil.py`` at the max count (2R+1)²−1.
- ``f32`` (host default): f32 GEMMs with **digit packing** — d torus
  column groups ride one f32 word as base-b digits (b a power of two >
  (2R+1)², so window sums never carry between digits and stay < 2²⁴,
  f32's exact-integer range; the torus seam rotates digits in the pad
  columns).  Packing divides GEMM width and memory traffic by d: on this
  host's CPU it is what pushes the banded path past the shift-add kernel
  at 16384² for every measured R ≥ 2.

Counts are exact integers on every lane, so applying the existing rule
tables (``ops/rules.py`` masks via ``stencil.apply_rule``, LtL tables via
``ltl._apply``) is **bit-identical to the dense oracle by construction**
— certified through the PR 5 digest plane in ``bench_suite`` config 15
and ``tests/test_matmul_stencil.py``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.obs.programs import registered_jit
from akka_game_of_life_tpu.ops import guard
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule
from akka_game_of_life_tpu.ops.stencil import STATE_DTYPE, alive_mask, apply_rule

# Counts and digit-packed words must stay exact integers in f32.
_MAX_EXACT_F32 = 1 << 24
# Digit-packing depth cap: beyond 6 the per-digit bases stop fitting the
# f32 mantissa for any radius; 4 is the practical ceiling on power-of-two
# boards (d must divide the width).
_MAX_DIGITS = 6
# Row/column tile bound: measured knee on this host (bigger tiles burn
# O(K) MACs/cell for no GEMM-efficiency gain; smaller ones fragment the
# GEMMs below the rank-2 fast path).  Also the MXU-friendly multiple.
_MAX_TILE = 512

MODES = ("auto", "f32", "int8", "bf16")


def band_matrix(n: int, radius: int, wrap: bool = True) -> np.ndarray:
    """The (n, n) ±radius band matrix ``A_R`` (f32 ones), circulant when
    ``wrap`` — the mathematical object the blocked kernel evaluates.
    Exported for tests and for the continuous-CA work to build on."""
    a = np.zeros((n, n), np.float32)
    idx = np.arange(n)
    for k in range(-radius, radius + 1):
        if wrap:
            a[idx, (idx + k) % n] = 1.0
        else:
            j = idx + k
            ok = (j >= 0) & (j < n)
            a[idx[ok], j[ok]] = 1.0
    return a


def _band_slab(tile: int, radius: int) -> np.ndarray:
    """(tile, tile + 2·radius) slab of ``A_R``: row t has ones on columns
    t..t+2R — the per-tile GEMM operand (shared by every interior tile)."""
    slab = np.zeros((tile, tile + 2 * radius), np.float32)
    for t in range(tile):
        slab[t, t : t + 2 * radius + 1] = 1.0
    return slab


def _pick_tile(n: int) -> int:
    """Largest divisor of ``n`` at most ``_MAX_TILE`` (n itself when small
    or awkwardly prime — the guard prices the resulting full-band slab)."""
    if n <= _MAX_TILE:
        return n
    best = 1
    for k in range(1, int(math.isqrt(n)) + 1):
        if n % k == 0:
            for d in (k, n // k):
                if best < d <= _MAX_TILE:
                    best = d
    return best if best >= 8 else n


def _pick_digits(width: int, radius: int) -> Tuple[int, int]:
    """(digits, base) for f32 packing: the deepest d dividing ``width``
    whose packed window sums stay under 2²⁴ (base = next power of two
    above the max window sum, so digit extraction is exact floor-divs)."""
    wmax = (2 * radius + 1) ** 2
    base = 1 << max(1, (wmax + 1).bit_length())
    for d in range(_MAX_DIGITS, 0, -1):
        if width % d:
            continue
        if width // d < max(radius, 1):
            continue  # seam slivers need R columns per digit group
        if wmax * (base**d - 1) // (base - 1) < _MAX_EXACT_F32:
            return d, base
    return 1, base


@dataclasses.dataclass(frozen=True)
class MatmulPlan:
    """A validated banded-matmul execution plan for one (shape, R, mode).

    Built once per combination (lru-cached) at trace/closure-build time;
    construction runs the :mod:`ops/guard` intermediate-size check, so an
    infeasible plan raises with the shapes and the cap knob named before
    any device allocation happens."""

    height: int
    width: int
    radius: int
    mode: str  # resolved: f32 | int8 | bf16
    digits: int
    base: int
    row_tile: int
    col_tile: int
    est_bytes: int

    @property
    def packed_width(self) -> int:
        return self.width // self.digits


def _resolve_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(f"unknown matmul dtype mode {mode!r}; use {MODES}")
    if mode == "auto":
        return "int8" if jax.default_backend() == "tpu" else "f32"
    return mode


@functools.lru_cache(maxsize=None)
def plan_matmul(
    shape: Tuple[int, int],
    radius: int,
    mode: str = "auto",
    neighborhood: str = "box",
) -> MatmulPlan:
    """Validate and price a banded-matmul plan; raises ``ValueError`` with
    an actionable message for every infeasible request."""
    h, w = int(shape[-2]), int(shape[-1])
    if neighborhood != "box":
        raise ValueError(
            "kernel=matmul supports box (Moore) neighborhoods only: the "
            "von Neumann diamond is not separable into A_R·S·A_Rᵀ — use "
            "the cumsum-difference path on kernel=dense"
        )
    if min(h, w) < 2 * radius + 1:
        raise ValueError(
            f"kernel=matmul needs min(height, width) >= 2R+1 "
            f"({2 * radius + 1} for radius {radius}), got {h}x{w}: the "
            f"torus window must not wrap onto itself"
        )
    mode = _resolve_mode(mode)
    digits, base = _pick_digits(w, radius) if mode == "f32" else (1, 0)
    wd = w // digits
    kr, kc = _pick_tile(h), _pick_tile(wd)
    item = {"f32": 4, "int8": 1, "bf16": 2}[mode]
    acc_item = 4  # int32 / f32 accumulator planes
    planes = [
        ((h, wd + 2 * radius), item),  # packed, column-padded operand
        ((h, wd + 2 * radius), item),  # pass-1 row sums (operand dtype)
        ((h, wd), acc_item),  # pass-2 window sums (accumulator dtype)
        ((h, w), 4),  # unpacked int32 counts feeding the rule epilogue
        ((kr, kr + 2 * radius), item),  # row band slab
        ((kc, kc + 2 * radius), item),  # column band slab
    ]
    est = sum(guard.plane_bytes(s, i) for s, i in planes)
    detail = (
        "Shrink the board/radius, or use kernel=dense (the shift-add "
        "path keeps intermediates board-sized)."
    )
    if mode == "f32" and digits <= 2:
        # The documented PR 11 residue, surfaced at the point of failure:
        # digit depth must divide the width, so power-of-two widths cap
        # packing at d=2 where a 3-divisible width would pack deeper and
        # shrink every packed plane by the same factor.
        w3 = guard.nearest_3smooth(w)
        d3, _ = _pick_digits(w3, radius)
        if d3 > digits:
            detail += (
                f" Or pad the width to the nearest 3-smooth size "
                f"({w} → {w3}): depth-{digits} digit packing is the "
                f"power-of-two-width cap here, while width {w3} packs "
                f"d={d3} digits and divides the packed planes (and the "
                f"GEMM width) by {d3}/{digits}."
            )
    guard.require_intermediates_fit(
        est,
        what=f"kernel=matmul ({mode}, {h}x{w}, radius {radius})",
        detail=detail,
        shapes=planes,
    )
    return MatmulPlan(h, w, radius, mode, digits, base, kr, kc, est)


def _operand_dtype(plan: MatmulPlan):
    return {"f32": jnp.float32, "int8": jnp.int8, "bf16": jnp.bfloat16}[plan.mode]


def _accum_dtype(plan: MatmulPlan):
    return jnp.int32 if plan.mode == "int8" else jnp.float32


def _dot(a: jax.Array, b: jax.Array, plan: MatmulPlan) -> jax.Array:
    """Rank-2 banded-slab product with overflow-safe accumulation: int8
    operands accumulate to int32, bf16/f32 to f32 — counts never wrap."""
    return jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=_accum_dtype(plan),
    )


def _packed_window_sums(alive: jax.Array, plan: MatmulPlan) -> jax.Array:
    """(H, W) 0/1 alive plane → (H, W/digits) window sums in the packed
    (digit-carrying) accumulator layout — the two blocked banded matrix
    multiplies without the unpack, so consumers can fuse digit extraction
    into their own epilogue instead of materializing an int32 board."""
    h, w, r = plan.height, plan.width, plan.radius
    d, wd = plan.digits, plan.packed_width
    od = _operand_dtype(plan)

    # 1. Pack: d torus column groups per word as base-b digits (d == 1 is
    # the identity cast).  Fused by XLA into one pass over the board.
    if d > 1:
        pows = [float(plan.base) ** i for i in range(d)]
        packed = alive[:, :wd].astype(od) * pows[0]
        for i in range(1, d):
            packed = packed + alive[:, i * wd : (i + 1) * wd].astype(od) * pows[i]
        p_hi = pows[-1]
        base = float(plan.base)
        # Torus seam: column -k carries x[:, m·wd - k] in digit m, i.e.
        # the neighbor word's digits rotated up (and symmetrically down on
        # the right).  Exact: values are integers < 2²⁴ and base is a
        # power of two, so the floor-divisions are exact.
        left = packed[:, wd - r :]
        right = packed[:, :r]
        left = jnp.floor(left / p_hi) + (left % p_hi) * base
        right = jnp.floor(right / base) + (right % base) * p_hi
    else:
        packed = alive.astype(od)
        left = packed[:, wd - r :]
        right = packed[:, :r]
    x_cp = jnp.concatenate([left, packed, right], axis=1)  # (h, wd + 2r)

    # 2. Row pass: y = A_R · x, tiled over rows.  Interior tiles read
    # contiguous slices; the torus wrap rides in the edge tiles' operands
    # (small concats), so no full padded copy is ever materialized.
    kr = plan.row_tile
    nbr = h // kr
    slab_r = jnp.asarray(_band_slab(kr, r).astype(od))
    rows = []
    for c in range(nbr):
        if nbr == 1:
            op = jnp.concatenate([x_cp[h - r :], x_cp, x_cp[:r]], axis=0)
        elif c == 0:
            op = jnp.concatenate([x_cp[h - r :], x_cp[: kr + r]], axis=0)
        elif c == nbr - 1:
            op = jnp.concatenate([x_cp[c * kr - r :], x_cp[:r]], axis=0)
        else:
            op = jax.lax.dynamic_slice_in_dim(x_cp, c * kr - r, kr + 2 * r, axis=0)
        rows.append(_dot(slab_r, op, plan))
    # Row sums ≤ (2R+1)·digit ≤ 21 per digit: exact back in operand dtype.
    y = jnp.concatenate(rows, axis=0).astype(od)  # (h, wd + 2r), col-padded

    # 3. Column pass: W = y · A_Rᵀ, tiled over packed columns.  The column
    # pads (with their seam digit rotation) were carried through the row
    # pass, so every tile — edges included — is one contiguous slice.
    kc = plan.col_tile
    nbc = wd // kc
    slab_ct = jnp.asarray(_band_slab(kc, r).T.astype(od))
    cols = [
        _dot(
            jax.lax.dynamic_slice_in_dim(y, c * kc, kc + 2 * r, axis=1),
            slab_ct,
            plan,
        )
        for c in range(nbc)
    ]
    return jnp.concatenate(cols, axis=1)  # (h, wd) accumulator dtype


def _extract_digit(packed_sums: jax.Array, plan: MatmulPlan, i: int) -> jax.Array:
    """Digit ``i`` of the packed window sums as int32 — exact, because
    values are integers < 2²⁴ and the base is a power of two, so the
    floor-division is a representable scale."""
    if plan.digits == 1:
        return packed_sums.astype(jnp.int32)
    base = float(plan.base)
    return (jnp.floor(packed_sums / base**i) % base).astype(jnp.int32)


def window_counts_matmul(alive: jax.Array, plan: MatmulPlan) -> jax.Array:
    """(H, W) 0/1 alive plane → (H, W) int32 window sums INCLUDING the
    center, on a torus, as two blocked banded matrix multiplies."""
    out_p = _packed_window_sums(alive, plan)
    if plan.digits == 1:
        return _extract_digit(out_p, plan, 0)
    return jnp.concatenate(
        [_extract_digit(out_p, plan, i) for i in range(plan.digits)], axis=1
    )


def neighbor_counts_matmul(
    alive: jax.Array, radius: int = 1, mode: str = "auto"
) -> jax.Array:
    """Torus neighbor counts EXCLUDING the center — the banded-matmul twin
    of ``stencil.neighbor_counts`` (R=1) and the LtL window sums (R>1)."""
    plan = plan_matmul(tuple(alive.shape), radius, mode)
    window = window_counts_matmul(alive, plan)
    return window - alive.astype(jnp.int32)


def step_matmul(state: jax.Array, rule, mode: str = "auto") -> jax.Array:
    """One toroidal CA step with banded-matmul neighbor counts.  Supports
    every rule family whose window is the Moore box: binary/Generations
    totalistic, wireworld, and box-neighborhood LtL (the diamond refuses
    in ``plan_matmul``).  Bit-identical to ``stencil.step`` /
    ``ltl.step_ltl`` by construction: the counts are exact integers and
    the rule epilogues are the existing ones.

    The rule is applied per digit group straight off the packed window
    sums — digit extraction fuses into the epilogue's elementwise pass,
    so no full-board int32 counts plane is ever materialized (a ~1 GiB
    round trip at 16384² that the A/B showed on the critical path)."""
    rule = resolve_rule(rule)
    plan = plan_matmul(tuple(state.shape), rule.radius, mode, rule.neighborhood)
    alive = alive_mask(state)
    out_p = _packed_window_sums(alive, plan)
    wd = plan.packed_width

    def _epilogue(state_slab, neighbors):
        if rule.kind == "ltl":
            from akka_game_of_life_tpu.ops import ltl

            return ltl._apply(state_slab, neighbors, rule)
        return apply_rule(state_slab, neighbors, rule)

    if plan.digits == 1:
        window = _extract_digit(out_p, plan, 0)
        return _epilogue(state, window - alive.astype(jnp.int32))
    parts = []
    for i in range(plan.digits):
        sl = slice(i * wd, (i + 1) * wd)
        window = _extract_digit(out_p, plan, i)
        parts.append(
            _epilogue(state[:, sl], window - alive[:, sl].astype(jnp.int32))
        )
    return jnp.concatenate(parts, axis=1)


@functools.lru_cache(maxsize=None)
def matmul_multi_step_fn(
    rule_key, n_steps: int, mode: str = "auto"
) -> Callable[[jax.Array], jax.Array]:
    """A jitted ``n_steps``-per-call banded-matmul closure (cached per
    (rule, n, mode)) — the ``kernel=matmul`` stepper Simulation mounts."""
    rule = resolve_rule(rule_key)

    @jax.jit
    def _run(state: jax.Array) -> jax.Array:
        def body(s, _):
            return step_matmul(s, rule, mode), None

        out, _ = jax.lax.scan(body, state, None, length=n_steps)
        return out

    def _cost(state):
        h, w = int(state.shape[-2]), int(state.shape[-1])
        # The plan priced these intermediates at closure-build time;
        # lru_cache makes the re-ask free after the first call.
        plan = plan_matmul((h, w), rule.radius, mode)
        return {
            "cells": float(h) * w * n_steps,
            "bytes": float(plan.est_bytes) * n_steps,
            # Two banded GEMM passes per step over the packed operand.
            "flops": 4.0 * h * plan.packed_width
            * (2 * rule.radius + 1) * n_steps,
        }

    return registered_jit(
        "matmul", ("multi_step", rule.name, mode, n_steps), _run, cost=_cost
    )
