"""Frontend horizontal scale-out: the gossiped shard-map federation.

docs/OPERATIONS.md "Frontend scale-out & HA".  N frontend processes run
behind ordinary HTTP load balancing, each owning a *slice* of the serve
keyspace, with no coordinator.  The PR 13 crc32 shard hash extends one
level up: ``shard_of(sid)`` still picks the shard, and a rendezvous hash
over the live frontends (the PR 14 sticky-replica discipline, shared
:func:`rendezvous_pick`) picks the shard's owning *frontend* — so any
frontend can answer any request:

- an op for a self-owned slice goes straight to the local
  :class:`~akka_game_of_life_tpu.serve.cluster.ClusterServePlane`;
- a create/step/delete for a foreign slice forwards over the peer link
  (``P_FWD_OPS``/``P_FWD_RESULT``, per-peer FIFO — one executor thread
  per origin on the owner, so two ops from one tenant connection can
  never reorder);
- a GET (the one fat payload: it carries the board) answers a 307
  redirect to the owner's own HTTP endpoint instead of hauling cells
  through a middleman.

Frontends discover each other from ``--frontend-seeds`` and gossip
membership + slice-table deltas (LWW by version) + cluster-budget shares
over the peer plane (``P_GOSSIP``), aged by the same
:class:`~akka_game_of_life_tpu.runtime.membership.Membership` machinery
workers use.  Each frontend streams its slice of control state — session
index rows, replication watermarks, tiled-session certified floors — to a
rendezvous-chosen *standby* peer (``P_REPLICATE``/``P_REPLICATE_ACK``,
the PR 14 seq/ack watermark discipline at shard granularity).

Failure discipline (the split-brain guard): silence alone never moves
ownership.  A peer whose gossip goes stale past
``frontend_gossip_timeout_s`` is SUSPECT — ops for its slices park with
the retryable 429 ``partitioned`` (never a double-owner, never a 404).
A peer is CONFIRMED dead only on link EOF *plus* a redial that gets
connection-refused (process gone, port unbound) — then its standby
promotes the replicated rows onto its local plane
(``begin_federation_promotion``: windowed ops answer retryable 429
``failover``), the dead peer's workers re-home their control channel to
a fallback frontend from the ``FED_PEERS`` list and announce their
session truth with ``SHARD_HOME``, which closes the window with zero
admitted sessions lost.
"""

from __future__ import annotations

import itertools
import queue
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.membership import Membership
from akka_game_of_life_tpu.runtime.wire import dial
from akka_game_of_life_tpu.serve.sessions import (
    AdmissionError,
    rendezvous_pick,
    shard_of,
)

# A forwarded op rides two HTTP-ish hops; give it the cluster op budget
# plus slack for the owner's own worker round-trip.
FWD_TIMEOUT_S = 15.0
# Confirmed-death probe: how long a redial may take before it reads as
# "unreachable" (partition) rather than "refused" (dead).
PROBE_TIMEOUT_S = 1.0
# A federation promotion window with no SHARD_HOME closes honestly after
# this many gossip timeouts (the dead frontend's workers died with it).
REHOME_GRACE_TIMEOUTS = 6.0
# Bounded auto-sid mining: expected attempts ≈ live frontends, so this
# bound is never reached in practice (the canary sid-mining discipline).
SID_MINE_ATTEMPTS = 4096


class FederationRedirect(Exception):
    """A request whose payload is too fat to proxy (GET ``/boards/<id>``
    carries the board): answer 307 with the owning frontend's URL.  The
    HTTP layer (``BoardsRoute._respond``) maps this to a ``Location``
    header; every other surface treats it as an error."""

    def __init__(self, url: str) -> None:
        super().__init__(url)
        self.url = url


def parse_seeds(spec: str) -> List[Tuple[str, int]]:
    """``host:port,host:port`` → [(host, port)] (config validated the
    shape; this just splits)."""
    out: List[Tuple[str, int]] = []
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


class _Moved(Exception):
    """Owner-side: the forwarded op's slice moved after the origin routed
    it — carries the owner this side currently believes in, so the origin
    can retry toward it exactly once."""

    def __init__(self, owner: str) -> None:
        super().__init__(owner)
        self.owner = owner


class _OriginExec:
    """One FIFO executor per origin frontend: forwarded ops from one peer
    execute strictly in arrival order (the per-peer wire FIFO extended
    through execution), while different origins proceed in parallel."""

    def __init__(self, fed: "FederationPlane", origin: str) -> None:
        self.q: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, args=(fed, origin), daemon=True,
            name=f"fed-exec-{origin}",
        )
        self._thread.start()

    def _run(self, fed: "FederationPlane", origin: str) -> None:
        while True:
            msg = self.q.get()
            if msg is None:
                return
            fed._exec_fwd(origin, msg)

    def close(self) -> None:
        self.q.put(None)


class _Peer:
    """One live peer frontend: its identity, addresses, and the single
    FIFO channel both directions of traffic ride."""

    __slots__ = ("name", "channel", "advertise", "cluster", "http_port",
                 "dialer", "slices")

    def __init__(self, name: str, channel, *, advertise, cluster,
                 http_port: int, dialer: str) -> None:
        self.name = name
        self.channel = channel
        self.advertise = tuple(advertise)  # (host, port) peers dial
        self.cluster = tuple(cluster)      # (host, port) workers dial
        self.http_port = int(http_port)    # tenant/obs endpoint
        self.dialer = dialer               # which side dialed (dedupe key)
        self.slices = 0                    # last gossiped owned count


class FederationPlane:
    """Peer-plane state machine for ONE frontend process.  Wraps (never
    replaces) the local :class:`ClusterServePlane`; the tenant surface
    mounts :attr:`router`, a :class:`FederatedRouter` exposing the same
    SessionRouter shape ``BoardsRoute`` already speaks.

    Lock discipline mirrors the plane's: ``self._lock`` orders the peer
    table, the slice map, and forwarding bookkeeping; NOTHING is sent on
    the wire while it is held (channel sends are themselves
    thread-safe)."""

    def __init__(self, config, plane, *, name: str,
                 cluster_addr: Tuple[str, int], events=None) -> None:
        self.config = config
        self.plane = plane
        self.name = name
        self.cluster_addr = tuple(cluster_addr)
        self.http_port = 0  # set once the obs endpoint binds
        self.events = events
        self.metrics = plane.metrics
        self.tracer = plane.tracer
        self.router = FederatedRouter(self)
        self.n_shards = plane.n_shards

        self.gossip_interval_s = float(config.frontend_gossip_interval_s)
        self.gossip_timeout_s = float(config.frontend_gossip_timeout_s)
        self.replicate_every = int(config.frontend_replicate_every)
        self.replicate_interval_s = float(config.frontend_replicate_interval_s)
        self._seeds = parse_seeds(config.frontend_seeds)

        self._lock = threading.RLock()
        self.membership = Membership(self.gossip_timeout_s)
        self.peers: Dict[str, _Peer] = {}  # graftlint: guarded-by _lock
        self._suspect: set = set()  # graftlint: guarded-by _lock
        # Peers whose slice table we have merged at least once; claiming
        # "unowned" slices is gated on it (see _claim_unowned_locked).
        self._gossip_heard: set = set()  # graftlint: guarded-by _lock
        self._dead: Dict[str, float] = {}  # graftlint: guarded-by _lock
        self._probing: set = set()  # graftlint: guarded-by _lock
        # shard → (owner frontend, version): the federated slice map,
        # merged LWW by version (ties break to the larger name — both
        # sides compute the same winner with no coordinator).
        self.slices: Dict[int, Tuple[str, int]] = {}  # graftlint: guarded-by _lock
        self._budget: Dict[str, dict] = {}  # graftlint: guarded-by _lock
        # Known member addresses (relayed via gossip for transitive
        # discovery): name → {"advertise": (h, p), "cluster": (h, p),
        # "http": port}.
        self._known: Dict[str, dict] = {}  # graftlint: guarded-by _lock

        # Forwarding: rid → {"ev", "result"}.
        self._fwd: Dict[int, dict] = {}  # graftlint: guarded-by _lock
        self._rids = itertools.count(1)
        self._exec: Dict[str, _OriginExec] = {}  # graftlint: guarded-by _lock

        # Control-state replication (origin side): sid → (epoch, digest)
        # the standby has ACKED; seq → (updates, drops) in flight.
        self._repl_acked: Dict[str, tuple] = {}  # graftlint: guarded-by _lock
        self._repl_inflight: Dict[int, tuple] = {}  # graftlint: guarded-by _lock
        self._repl_seq = itertools.count(1)
        self._standby: Optional[str] = None  # graftlint: guarded-by _lock
        # Standby side: origin → {sid: row} (the peer's replicated slice
        # of control state, promoted on confirmed death).
        self._store: Dict[str, Dict[str, dict]] = {}  # graftlint: guarded-by _lock
        # Federation promotion windows awaiting SHARD_HOME: shard → deadline.
        self._promote_deadline: Dict[int, float] = {}  # graftlint: guarded-by _lock

        self._sid_counter = itertools.count(1)
        self._sid_prefix = f"s{abs(hash(name)) & 0xFFFF:04x}-"
        self._stop = threading.Event()
        self._on_peers_changed = None  # frontend hook: push FED_PEERS

        self._m_peers = self.metrics.gauge(
            "gol_frontend_peers", "Live federation peer frontends", ()
        )
        self._m_gossip_age = self.metrics.gauge(
            "gol_frontend_gossip_age_seconds",
            "Seconds since the last gossip/frame from each peer frontend",
            ("peer",),
        )
        self._m_fwd_ops = self.metrics.counter(
            "gol_frontend_forwarded_ops_total"
        )
        self._m_redirects = self.metrics.counter(
            "gol_frontend_forward_redirects_total"
        )
        self._m_promotions = self.metrics.counter(
            "gol_frontend_slice_promotions_total"
        )
        self._m_slices = self.metrics.gauge(
            "gol_frontend_slices_owned",
            "Serve-keyspace slices this frontend owns", ()
        )
        self._m_parked = self.metrics.counter(
            "gol_frontend_parked_ops_total"
        )
        self._m_repl_rows = self.metrics.counter(
            "gol_frontend_replicated_rows_total"
        )
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for fn in (self._gossip_loop, self._replicate_loop):
            t = threading.Thread(target=fn, daemon=True, name=fn.__name__)
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            peers = list(self.peers.values())
            self.peers.clear()
            execs = list(self._exec.values())
            self._exec.clear()
            for slot in self._fwd.values():
                slot["result"] = {
                    "ok": False,
                    "error": {"kind": "error", "detail": "federation closed"},
                }
                slot["ev"].set()
            self._fwd.clear()
        for ex in execs:
            ex.close()
        for p in peers:
            try:
                p.channel.close()
            except OSError:
                pass

    # -- identity / addressing -----------------------------------------------

    def set_http_port(self, port: int) -> None:
        self.http_port = int(port)

    def on_peers_changed(self, fn) -> None:
        """Frontend hook: called (outside the lock) whenever the live
        peer set changes, so workers get a fresh FED_PEERS fallback
        list."""
        self._on_peers_changed = fn

    def worker_fallbacks(self) -> List[List]:
        """Live peers' cluster (worker-listener) addresses — the control
        re-home targets a WELCOME/FED_PEERS frame carries."""
        alive = {m.name for m in self.membership.alive_members()}
        with self._lock:
            return [
                [p.cluster[0], p.cluster[1]]
                for n, p in sorted(self.peers.items()) if n in alive
            ]

    def _hello_doc(self) -> dict:
        return {
            "type": P.P_HELLO,
            "name": self.name,
            "advertise": list(self.cluster_addr),
            "cluster": list(self.cluster_addr),
            "http": self.http_port,
            "dialer": "",  # stamped by the dialing side
        }

    # -- peer connections ----------------------------------------------------

    def serve_peer(self, channel, hello: dict) -> None:
        """Acceptor side: a freshly accepted connection whose first frame
        was a P_HELLO (the frontend's listener hands it over).  Replies
        with our own hello, registers the peer, then reads frames until
        EOF — this IS the connection's reader thread."""
        name = str(hello.get("name") or "")
        if not name or name == self.name:
            channel.close()
            return
        try:
            channel.send(self._hello_doc())
        except OSError:
            channel.close()
            return
        if not self._register_peer(channel, hello,
                                   dialer=str(hello.get("dialer") or name)):
            channel.close()
            return
        self._read_peer(name, channel)

    def _dial_peer(self, host: str, port: int) -> bool:
        """Dialer side: connect, exchange hellos, register, spawn the
        reader.  Returns True when a live peer link came up."""
        try:
            channel = dial(host, port, timeout_s=PROBE_TIMEOUT_S,
                           send_deadline_s=self.config.send_deadline_s)
            doc = self._hello_doc()
            doc["dialer"] = self.name
            channel.send(doc)
            hello = channel.recv()
        except (OSError, ValueError):
            return False
        if (
            not isinstance(hello, dict)
            or hello.get("type") != P.P_HELLO
            or not hello.get("name")
            or hello["name"] == self.name
        ):
            channel.close()
            return False
        if not self._register_peer(channel, hello, dialer=self.name):
            channel.close()
            return False
        name = str(hello["name"])
        t = threading.Thread(
            target=self._read_peer, args=(name, channel), daemon=True,
            name=f"fed-peer-{name}",
        )
        t.start()
        return True

    def _register_peer(self, channel, hello: dict, *, dialer: str) -> bool:
        """Install (or dedupe) one peer link.  Simultaneous mutual dials
        produce two connections for one name; both sides keep the one
        whose DIALER is the lexicographically smaller frontend — a
        deterministic rule needing no extra round-trip."""
        name = str(hello["name"])
        peer = _Peer(
            name, channel,
            advertise=hello.get("advertise") or [channel.sock.getpeername()[0], 0],
            cluster=hello.get("cluster") or hello.get("advertise") or ["", 0],
            http_port=int(hello.get("http", 0) or 0),
            dialer=dialer,
        )
        with self._lock:
            old = self.peers.get(name)
            if old is not None and old.channel is not channel:
                # Keep the link dialed by min(name): both ends agree.
                if min(old.dialer, peer.dialer) == old.dialer:
                    return False
                try:
                    old.channel.close()
                except OSError:
                    pass
            self.peers[name] = peer
            self._dead.pop(name, None)
            self._suspect.discard(name)
            # A (re)joined incarnation must gossip its table before it
            # counts as heard — pause unowned-slice claims one round.
            self._gossip_heard.discard(name)
            # A restarted peer comes back empty: its OLD replicated rows
            # describe sessions that no longer exist anywhere.
            self._store.pop(name, None)
            self._known[name] = {
                "advertise": peer.advertise, "cluster": peer.cluster,
                "http": peer.http_port,
            }
        m = self.membership.get(name)
        if m is None or not m.alive:
            self.membership.register(
                channel, name,
                peer_host=peer.advertise[0], peer_port=int(peer.advertise[1]),
            )
        else:
            m.channel = channel
            self.membership.beat(name)
        if self.events is not None:
            self.events.emit("frontend_peer_joined", peer=name)
        self._refresh_gauges()
        self._notify_peers_changed()
        return True

    def _read_peer(self, name: str, channel) -> None:
        try:
            while not self._stop.is_set():
                msg = channel.recv()
                if msg is None:
                    break
                if isinstance(msg, dict):
                    self._on_peer_msg(name, msg)
        except (OSError, ValueError):
            pass
        finally:
            with self._lock:
                peer = self.peers.get(name)
                stale = peer is not None and peer.channel is channel
            if stale and not self._stop.is_set():
                self._on_peer_link_down(name)

    # -- failure detection ---------------------------------------------------

    def _on_peer_link_down(self, name: str) -> None:
        """Link EOF: probe the peer's address until the verdict resolves.
        Connection-refused means the process is gone (port unbound) —
        CONFIRMED dead, promote.  Anything else (timeout, unreachable, or
        an accepting socket) is a partition or restart-in-progress:
        SUSPECT, park, and probe again from this (now otherwise idle)
        reader thread — a one-shot verdict would let a single transient
        non-refused probe park the peer's slices forever."""
        with self._lock:
            if name in self._probing:
                return
            self._probing.add(name)
            peer = self.peers.get(name)
            down_channel = peer.channel if peer is not None else None
        suspected = False
        try:
            while not self._stop.is_set():
                verdict = self._probe(name)
                if verdict == "dead":
                    self._confirm_dead(name)
                    return
                with self._lock:
                    peer = self.peers.get(name)
                    if peer is None or peer.channel is not down_channel:
                        # Re-registered (a restart dialed back in) or
                        # confirmed dead by another path: verdict settled.
                        return
                    self._suspect.add(name)
                if not suspected:
                    suspected = True
                    if self.events is not None:
                        self.events.emit(
                            "frontend_peer_suspect", peer=name,
                            verdict=verdict,
                        )
                if self._stop.wait(self.gossip_interval_s):
                    return
        finally:
            with self._lock:
                self._probing.discard(name)

    def _probe(self, name: str) -> str:
        with self._lock:
            peer = self.peers.get(name)
            addr = peer.advertise if peer is not None else (
                self._known.get(name, {}).get("advertise")
            )
        if not addr or not addr[0]:
            return "unknown"
        try:
            s = socket.create_connection(
                (addr[0], int(addr[1])), timeout=PROBE_TIMEOUT_S
            )
            s.close()
            return "accepting"  # something listens there: NOT provably dead
        except ConnectionRefusedError:
            return "dead"
        except OSError:
            return "partitioned"

    def _confirm_dead(self, name: str) -> None:
        """EOF + redial-refused: the peer process is gone.  Its standby
        (rendezvous over the survivors) adopts ALL of its slices and
        promotes the replicated control rows; everyone else just marks
        the owner dead (ops park retryable until the standby's claims
        gossip in)."""
        self.membership.mark_dead(name)
        rows: List[dict] = []
        adopt: List[int] = []
        with self._lock:
            peer = self.peers.pop(name, None)
            self._suspect.discard(name)
            self._dead[name] = time.monotonic()
            survivors = sorted(
                {self.name}
                | {m.name for m in self.membership.alive_members()}
            )
            standby = rendezvous_pick(f"fe-standby:{name}", survivors)
            if standby == self.name:
                rows = list(self._store.pop(name, {}).values())
                deadline = time.monotonic() + max(
                    10.0, REHOME_GRACE_TIMEOUTS * self.gossip_timeout_s
                )
                for shard, (owner, version) in self.slices.items():
                    if owner == name:
                        self.slices[shard] = (self.name, version + 1)
                        adopt.append(shard)
                        self._promote_deadline[shard] = deadline
            # Unanswered forwarded ops toward the dead peer fail fast as
            # retryable (never silently lost).
            for rid, slot in list(self._fwd.items()):
                if slot.get("peer") == name:
                    slot["result"] = {
                        "ok": False,
                        "error": {
                            "kind": "admission", "reason": "failover",
                            "detail": f"frontend {name} died mid-forward; "
                                      f"retry",
                        },
                    }
                    slot["ev"].set()
                    del self._fwd[rid]
            ex = self._exec.pop(name, None)
        if ex is not None:
            ex.close()
        if peer is not None:
            try:
                peer.channel.close()
            except OSError:
                pass
        # Label-cardinality reclaim: a dead peer must not export forever.
        self._m_gossip_age.remove(peer=name)
        if self.events is not None:
            self.events.emit(
                "frontend_peer_dead", peer=name,
                standby=standby, slices_adopted=len(adopt),
            )
        if adopt:
            self._m_promotions.inc(len(adopt))
            self.plane.begin_federation_promotion(rows, origin=name)
        self._refresh_gauges()
        self._notify_peers_changed()

    # -- gossip --------------------------------------------------------------

    def _gossip_loop(self) -> None:
        while not self._stop.wait(self.gossip_interval_s):
            try:
                self._dial_missing()
                self._gossip_tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass

    def _dial_missing(self) -> None:
        """Connect to every seed and every gossip-learned member we hold
        no live link to (transitive discovery — a new frontend needs only
        ONE live seed to find the whole federation)."""
        targets: List[Tuple[str, int]] = []
        with self._lock:
            connected = set(self.peers)
            known = dict(self._known)
        for host, port in self._seeds:
            if (host, port) == self.cluster_addr:
                continue
            if any(
                tuple(meta["advertise"]) == (host, port)
                for n, meta in known.items() if n in connected
            ):
                continue
            targets.append((host, port))
        for name, meta in known.items():
            if name in connected or name == self.name:
                continue
            addr = tuple(meta["advertise"])
            if addr not in targets and addr != self.cluster_addr:
                targets.append(addr)
        for host, port in targets:
            if self._stop.is_set():
                return
            self._dial_peer(host, port)

    def _gossip_tick(self) -> None:
        now = time.monotonic()
        alive = {m.name: m for m in self.membership.alive_members()}
        with self._lock:
            self._claim_unowned_locked(alive)
            self._release_empty_locked(alive)
            doc = self._gossip_doc_locked(alive, now)
            channels = [
                (n, p.channel) for n, p in self.peers.items() if n in alive
            ]
            # Suspects age in and out with evidence: traffic resumed →
            # clear; stale past the timeout with a live link → suspect.
            for name, m in alive.items():
                age = now - m.last_seen
                self._m_gossip_age.labels(peer=name).set(round(age, 3))
                if age > self.gossip_timeout_s:
                    if name not in self._suspect:
                        self._suspect.add(name)
                        if self.events is not None:
                            self.events.emit(
                                "frontend_peer_suspect", peer=name,
                                verdict="gossip_stale",
                            )
                else:
                    self._suspect.discard(name)
        for _name, ch in channels:
            try:
                ch.send(doc)
            except OSError:
                pass  # the reader thread's EOF path owns the verdict
        self._expire_promotions(now)
        self._refresh_gauges()

    def _claim_unowned_locked(self, alive: dict) -> None:
        """Claim UNOWNED slices whose rendezvous-desired owner is this
        frontend.  Never claims an owned slice — ownership moves only by
        owner-initiated release (empty slices) or confirmed-death
        promotion; that asymmetry is the split-brain guard."""
        for name in alive:
            if name not in self._gossip_heard and name not in self._suspect:
                # A live peer whose slice table we have never merged: a
                # shard that LOOKS unowned may carry its claim — a fresh
                # boot that claimed here would steal owned slices and
                # bounce forwarded ops off an owner with no session rows.
                # One gossip round (or the stale-suspect timeout) settles
                # which shards are genuinely unowned.
                return
        names = sorted({self.name} | set(alive))
        for shard in range(self.n_shards):
            if shard in self.slices:
                continue
            if rendezvous_pick(f"slice:{shard}", names) == self.name:
                self.slices[shard] = (self.name, 1)

    def _release_empty_locked(self, alive: dict) -> None:
        """The elastic planner's FOURTH resource type: EMPTY self-owned
        slices flip (budget-free, like ``plan_shards`` empties) to their
        rendezvous-desired owner, so a late-joining frontend absorbs its
        share of an idle keyspace in one gossip round."""
        live = sorted({self.name} | set(alive))
        if len(live) < 2:
            return
        weights: Dict[int, int] = {}
        for sid, e in self.plane.sessions.items():  # graftlint: waive GL-LOCK01 -- advisory read: a racing create lands in a slice this pass then skips (non-zero weight next pass); release correctness re-checks nothing
            s = shard_of(sid, self.n_shards)
            weights[s] = weights.get(s, 0) + 1
        owners = {
            s: rec[0] for s, rec in self.slices.items() if rec[0] == self.name
        }
        for shard, _src, dest in self.plane.rebalancer.plan_slices(
            owners, weights, live, self.name,
        ):
            _owner, version = self.slices[shard]
            self.slices[shard] = (dest, version + 1)

    def _gossip_doc_locked(self, alive: dict, now: float) -> dict:
        members = {
            self.name: {
                "advertise": list(self.cluster_addr),
                "cluster": list(self.cluster_addr),
                "http": self.http_port,
            }
        }
        for name, meta in self._known.items():
            if name in alive:
                members[name] = {
                    "advertise": list(meta["advertise"]),
                    "cluster": list(meta["cluster"]),
                    "http": meta["http"],
                }
        stats = self.plane.stats()
        self._budget[self.name] = {
            "sessions": stats["sessions"], "cells": stats["cells"],
        }
        return {
            "type": P.P_GOSSIP,
            "from": self.name,
            "members": members,
            "slices": {str(s): [o, v] for s, (o, v) in self.slices.items()},
            "budget": dict(self._budget[self.name]),
            "owned": sum(
                1 for o, _v in self.slices.values() if o == self.name
            ),
        }

    def _merge_gossip(self, origin: str, msg: dict) -> None:
        self.membership.beat(origin)
        members = msg.get("members") or {}
        slices = msg.get("slices") or {}
        budget = msg.get("budget")
        with self._lock:
            self._gossip_heard.add(origin)
            for name, meta in members.items():
                if name == self.name or not isinstance(meta, dict):
                    continue
                if meta.get("advertise"):
                    self._known[name] = {
                        "advertise": tuple(meta["advertise"]),
                        "cluster": tuple(
                            meta.get("cluster") or meta["advertise"]
                        ),
                        "http": int(meta.get("http", 0) or 0),
                    }
            if isinstance(budget, dict):
                self._budget[origin] = {
                    "sessions": int(budget.get("sessions", 0)),
                    "cells": int(budget.get("cells", 0)),
                }
            peer = self.peers.get(origin)
            if peer is not None:
                peer.slices = int(msg.get("owned", peer.slices))
            for key, rec in slices.items():
                try:
                    shard = int(key)
                    owner, version = str(rec[0]), int(rec[1])
                except (TypeError, ValueError, IndexError):
                    continue
                if shard < 0 or shard >= self.n_shards:
                    continue
                mine = self.slices.get(shard)
                if mine is None:
                    self.slices[shard] = (owner, version)
                    continue
                if (version, owner) > (mine[1], mine[0]) and owner != mine[0]:
                    if mine[0] == self.name and self._slice_nonempty(shard):
                        # A conflicting claim would strand live local
                        # sessions: re-assert with a higher version (the
                        # non-empty side always wins — sessions never
                        # live-migrate between frontends).
                        self.slices[shard] = (self.name, version + 1)
                    else:
                        self.slices[shard] = (owner, version)
                elif (version, owner) > (mine[1], mine[0]):
                    self.slices[shard] = (owner, version)

    def _slice_nonempty(self, shard: int) -> bool:
        return any(
            shard_of(sid, self.n_shards) == shard
            for sid in self.plane.sessions  # graftlint: waive GL-LOCK01 -- GIL-atomic key scan; a stale row only delays one release pass
        )

    def _expire_promotions(self, now: float) -> None:
        expired: List[int] = []
        with self._lock:
            for shard, deadline in list(self._promote_deadline.items()):
                if now >= deadline:
                    del self._promote_deadline[shard]
                    expired.append(shard)
        for shard in expired:
            self.plane.expire_federation_promotion(shard)

    # -- peer frame dispatch -------------------------------------------------

    def _on_peer_msg(self, origin: str, msg: dict) -> None:
        kind = msg.get("type")
        self.membership.beat(origin)
        if kind == P.P_GOSSIP:
            self._merge_gossip(origin, msg)
        elif kind == P.P_FWD_OPS:
            with self._lock:
                ex = self._exec.get(origin)
                if ex is None:
                    ex = self._exec[origin] = _OriginExec(self, origin)
            ex.q.put(msg)
        elif kind == P.P_FWD_RESULT:
            with self._lock:
                slot = self._fwd.pop(int(msg.get("rid", 0)), None)
            if slot is not None:
                slot["result"] = msg
                slot["ev"].set()
        elif kind == P.P_REPLICATE:
            self._on_replicate(origin, msg)
        elif kind == P.P_REPLICATE_ACK:
            self._on_replicate_ack(origin, msg)

    # -- op forwarding (origin side) -----------------------------------------

    def owner_of(self, shard: int) -> str:
        """The shard's owning frontend — or a retryable 429 when the
        slice is unowned (bootstrap), its owner is suspect
        (``partitioned`` — the split-brain park), or its owner is
        confirmed dead with promotion still in flight (``failover``)."""
        with self._lock:
            rec = self.slices.get(shard)
            if rec is None:
                self.plane._reject(
                    "failover",
                    f"slice {shard} is unowned while the federation "
                    f"bootstraps; retry",
                )
            owner = rec[0]
            if owner == self.name:
                return owner
            if owner in self._suspect:
                self._m_parked.inc()
                self.plane._reject(
                    "partitioned",
                    f"slice {shard} owner {owner} is unreachable but not "
                    f"provably dead; writes park to avoid a split brain — "
                    f"retry",
                )
            peer = self.peers.get(owner)
        m = self.membership.get(owner)
        if peer is None or m is None or not m.alive:
            self.plane._reject(
                "failover",
                f"slice {shard} owner {owner} is down; its standby is "
                f"promoting — retry",
            )
        return owner

    def forward(self, owner: str, call: str, kwargs: dict,
                *, retried: bool = False):
        """Execute one router call on the owning frontend over the peer
        link.  Per-peer wire FIFO + per-origin executor = end-to-end
        FIFO.  A ``moved`` answer (the slice flipped after we routed)
        retries exactly once toward the owner's successor."""
        with self._lock:
            peer = self.peers.get(owner)
            if peer is None:
                self.plane._reject(
                    "failover", f"frontend {owner} is not connected; retry"
                )
            rid = next(self._rids)
            slot = {"ev": threading.Event(), "peer": owner}
            self._fwd[rid] = slot
        try:
            peer.channel.send({
                "type": P.P_FWD_OPS, "rid": rid, "call": call,
                "kwargs": kwargs, "origin": self.name,
            })
        except OSError:
            with self._lock:
                self._fwd.pop(rid, None)
            self.plane._reject(
                "failover", f"frontend {owner} link failed mid-send; retry"
            )
        self._m_fwd_ops.inc()
        if not slot["ev"].wait(FWD_TIMEOUT_S):
            with self._lock:
                self._fwd.pop(rid, None)
            raise TimeoutError(
                f"op forwarded to frontend {owner} timed out in flight"
            )
        res = slot["result"]
        if res.get("ok"):
            return res.get("value")
        err = res.get("error") or {}
        kind = err.get("kind")
        detail = str(err.get("detail", ""))
        if kind == "moved" and not retried:
            succ = str(err.get("owner") or "")
            if succ == self.name:
                raise _Moved(succ)  # caller re-runs locally
            if succ:
                return self.forward(succ, call, kwargs, retried=True)
        if kind == "admission":
            raise AdmissionError(str(err.get("reason", "failover")), detail)
        if kind == "key":
            raise KeyError(err.get("sid", detail))
        if kind == "value":
            raise ValueError(detail)
        if kind == "timeout":
            raise TimeoutError(detail)
        raise RuntimeError(f"forwarded op failed on {owner}: {detail}")

    # -- op forwarding (owner side) ------------------------------------------

    def _exec_fwd(self, origin: str, msg: dict) -> None:
        rid = int(msg.get("rid", 0))
        try:
            value = self._apply_local(
                str(msg.get("call", "")), msg.get("kwargs") or {}
            )
            reply = {"type": P.P_FWD_RESULT, "rid": rid, "ok": True,
                     "value": value}
        except _Moved as e:
            reply = self._fwd_error(rid, "moved", owner=e.owner)
        except AdmissionError as e:
            reply = self._fwd_error(
                rid, "admission", reason=e.reason, detail=str(e)
            )
        except KeyError as e:
            reply = self._fwd_error(rid, "key", sid=str(e.args[0]))
        except (ValueError, TypeError) as e:
            reply = self._fwd_error(rid, "value", detail=str(e))
        except TimeoutError as e:
            reply = self._fwd_error(rid, "timeout", detail=str(e))
        except Exception as e:  # noqa: BLE001 — every forwarded op answers
            reply = self._fwd_error(rid, "error", detail=repr(e))
        with self._lock:
            peer = self.peers.get(origin)
        if peer is None:
            return  # origin died mid-op; its failover path answered it
        try:
            peer.channel.send(reply)
        except OSError:
            pass

    @staticmethod
    def _fwd_error(rid: int, kind: str, **fields) -> dict:
        return {"type": P.P_FWD_RESULT, "rid": rid, "ok": False,
                "error": {"kind": kind, **fields}}

    def _apply_local(self, call: str, kwargs: dict):
        sid = str(kwargs.get("sid", ""))
        shard = shard_of(sid, self.n_shards)
        with self._lock:
            rec = self.slices.get(shard)
            if rec is None or rec[0] != self.name:
                raise _Moved(rec[0] if rec is not None else "")
        if call == "create":
            doc = self.plane.create(
                tenant=str(kwargs.get("tenant", "default")),
                rule=kwargs.get("rule", "conway"),
                height=int(kwargs.get("height", 64)),
                width=int(kwargs.get("width", 64)),
                seed=int(kwargs.get("seed", 0)),
                density=float(kwargs.get("density", 0.5)),
                with_board=False,  # fat payloads redirect, never forward
                sid=sid,
            )
            doc.pop("board", None)
            return doc
        if call == "step":
            epoch, digest = self.plane.step(
                sid, int(kwargs.get("steps", 1))
            )
            return [epoch, digest]
        if call == "delete":
            self.plane.delete(sid)
            return sid
        raise ValueError(f"unknown forwarded call {call!r}")

    # -- control-state replication -------------------------------------------

    def _replicate_loop(self) -> None:
        while not self._stop.wait(self.replicate_interval_s):
            try:
                self._replicate_tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass

    def _standby_locked(self) -> Optional[str]:
        alive = [
            m.name for m in self.membership.alive_members()
            if m.name not in self._suspect
        ]
        return rendezvous_pick(f"fe-standby:{self.name}", alive)

    def _replicate_tick(self) -> None:
        rows = self.plane.control_rows()
        with self._lock:
            standby = self._standby_locked()
            if standby != self._standby:
                # New standby (join/leave/promotion): reset and resend
                # the whole slice — the PR 14 stream-from-scratch rule.
                self._standby = standby
                self._repl_acked.clear()
                self._repl_inflight.clear()
                reset = True
            else:
                reset = False
            if standby is None:
                return
            mine = {
                r["sid"]: r for r in rows
                if self.slices.get(r["slice"], ("",))[0] == self.name
            }
            dirty = [
                r for sid, r in mine.items()
                if self._repl_acked.get(sid) != (r["epoch"], r["digest"])
                and not any(
                    sid in upd for upd, _ in self._repl_inflight.values()
                )
            ]
            gone = [
                sid for sid in self._repl_acked
                if sid not in mine and not any(
                    sid in drops for _, drops in self._repl_inflight.values()
                )
            ]
            frames = []
            batch = max(1, self.replicate_every)
            first = True
            while dirty or gone or (reset and first):
                chunk, dirty = dirty[:batch], dirty[batch:]
                drops, gone = gone[:batch], gone[batch:]
                seq = next(self._repl_seq)
                self._repl_inflight[seq] = (
                    {r["sid"]: (r["epoch"], r["digest"]) for r in chunk},
                    list(drops),
                )
                frames.append({
                    "type": P.P_REPLICATE, "seq": seq, "rows": chunk,
                    "drop": drops, "reset": reset and first,
                })
                first = False
            peer = self.peers.get(standby)
        if peer is None:
            return
        for frame in frames:
            try:
                peer.channel.send(frame)
            except OSError:
                return
            self._m_repl_rows.inc(len(frame["rows"]))

    def _on_replicate(self, origin: str, msg: dict) -> None:
        """Standby side: install the origin's rows, ACK the seq (the
        origin's watermark advances exactly like a worker's
        SHARD_REPLICATE_ACK)."""
        with self._lock:
            store = self._store.setdefault(origin, {})
            if msg.get("reset"):
                store.clear()
            for row in msg.get("rows") or []:
                if isinstance(row, dict) and row.get("sid"):
                    store[str(row["sid"])] = row
            for sid in msg.get("drop") or []:
                store.pop(str(sid), None)
            peer = self.peers.get(origin)
        if peer is None:
            return
        try:
            peer.channel.send({
                "type": P.P_REPLICATE_ACK, "seq": int(msg.get("seq", 0)),
            })
        except OSError:
            pass

    def _on_replicate_ack(self, origin: str, msg: dict) -> None:
        with self._lock:
            if origin != self._standby:
                return  # stale ack from a previous standby
            inflight = self._repl_inflight.pop(int(msg.get("seq", 0)), None)
            if inflight is None:
                return
            updates, drops = inflight
            self._repl_acked.update(updates)
            for sid in drops:
                self._repl_acked.pop(sid, None)

    # -- sid mining ----------------------------------------------------------

    def mine_local_sid(self) -> str:
        """An auto-generated session id whose crc32 shard lands in a
        self-owned slice (bounded attempts, the canary sid-mining
        discipline) — every session's sid hashes to a slice owned by its
        hosting frontend, so routing by ``shard_of(sid)`` is uniform."""
        with self._lock:
            owned = {
                s for s, (o, _v) in self.slices.items() if o == self.name
            }
        if not owned:
            self.plane._reject(
                "failover",
                "this frontend owns no slices yet (federation "
                "bootstrapping); retry",
            )
        for _ in range(SID_MINE_ATTEMPTS):
            sid = f"{self._sid_prefix}{next(self._sid_counter):08x}"
            if shard_of(sid, self.n_shards) in owned:
                return sid
        self.plane._reject(
            "failover", "could not mine a self-owned session id; retry"
        )
        raise AssertionError("unreachable")  # _reject always raises

    # -- cluster budget ------------------------------------------------------

    def check_cluster_budget(self, cells: int) -> None:
        """Gossiped budget shares make the cluster-wide caps meaningful
        across N frontends: the sum of everyone's shares (plus this
        create) must fit.  The local plane's ``_admit_locked`` stays as
        the per-process backstop."""
        max_sessions = self.plane.max_sessions
        max_cells = self.plane.max_cells
        with self._lock:
            alive = {m.name for m in self.membership.alive_members()}
            total_sessions = sum(
                b["sessions"] for n, b in self._budget.items()
                if n in alive and n != self.name
            )
            total_cells = sum(
                b["cells"] for n, b in self._budget.items()
                if n in alive and n != self.name
            )
        stats = self.plane.stats()
        total_sessions += stats["sessions"]
        total_cells += stats["cells"]
        if max_sessions and total_sessions + 1 > max_sessions:
            self.plane._reject(
                "max_sessions",
                f"cluster session budget exhausted "
                f"({total_sessions}/{max_sessions} across the federation)",
            )
        if max_cells and total_cells + cells > max_cells:
            self.plane._reject(
                "max_cells",
                f"cluster cell budget exhausted ({total_cells} + {cells} "
                f"> {max_cells} across the federation)",
            )

    # -- redirect targets ----------------------------------------------------

    def redirect_url(self, owner: str, sid: str) -> str:
        with self._lock:
            peer = self.peers.get(owner)
            meta = self._known.get(owner, {})
        host = peer.advertise[0] if peer is not None else (
            meta.get("advertise", ("", 0))[0]
        )
        http = peer.http_port if peer is not None else int(
            meta.get("http", 0) or 0
        )
        if not host or not http:
            self.plane._reject(
                "failover",
                f"frontend {owner} has no known HTTP endpoint yet; retry",
            )
        self._m_redirects.inc()
        return f"http://{host}:{http}/boards/{sid}"

    # -- observability -------------------------------------------------------

    def _refresh_gauges(self) -> None:
        alive = {m.name for m in self.membership.alive_members()}
        with self._lock:
            self._m_peers.set(len(alive & set(self.peers)))
            self._m_slices.set(sum(
                1 for o, _v in self.slices.values() if o == self.name
            ))

    def _notify_peers_changed(self) -> None:
        fn = self._on_peers_changed
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a push failure is advisory
                pass

    def health(self) -> dict:
        """The /healthz ``federation`` block: the peer view, the slice
        map, forwarded-op counters, promotion windows — what an operator
        checks first when one frontend of N misbehaves."""
        now = time.monotonic()
        alive = {m.name: m for m in self.membership.alive_members()}
        with self._lock:
            by_frontend: Dict[str, int] = {}
            for owner, _v in self.slices.values():
                by_frontend[owner] = by_frontend.get(owner, 0) + 1
            return {
                "name": self.name,
                "peers": {
                    name: {
                        "gossip_age_s": round(
                            max(0.0, now - alive[name].last_seen), 3
                        ) if name in alive else None,
                        "suspect": name in self._suspect,
                        "http": p.http_port,
                        "cluster": list(p.cluster),
                    }
                    for name, p in sorted(self.peers.items())
                },
                "suspect": sorted(self._suspect),
                "dead": sorted(self._dead),
                "slices": {
                    "total": self.n_shards,
                    "owned": by_frontend.get(self.name, 0),
                    "unowned": self.n_shards - sum(by_frontend.values()),
                    "by_frontend": by_frontend,
                },
                "standby": self._standby,
                "replicated_rows_held": {
                    origin: len(rows)
                    for origin, rows in sorted(self._store.items())
                },
                "forwarded_ops": int(self._m_fwd_ops.value),
                "forward_redirects": int(self._m_redirects.value),
                "parked_ops": int(self._m_parked.value),
                "promotions_inflight": len(self._promote_deadline),
                "budget": {
                    n: dict(b) for n, b in sorted(self._budget.items())
                },
            }


class FederatedRouter:
    """The SessionRouter-shaped surface ``BoardsRoute`` mounts when
    federation is on: resolves the owning frontend one level above the
    local plane's shard→worker table, then delegates, forwards, or
    redirects.  Everything else (config/metrics/tracer, the attributes
    the HTTP layer sniffs) passes through to the plane."""

    def __init__(self, fed: FederationPlane) -> None:
        self.fed = fed
        self.plane = fed.plane
        self.config = fed.plane.config
        self.metrics = fed.plane.metrics
        self.tracer = fed.plane.tracer

    def create(self, tenant: str = "default", rule="conway",
               height: int = 64, width: int = 64, seed: int = 0,
               density: float = 0.5, with_board: bool = True,
               sid: Optional[str] = None) -> dict:
        fed = self.fed
        fed.check_cluster_budget(int(height) * int(width))
        if sid is None:
            # Auto ids mine into a self-owned slice: creates stay local.
            sid = fed.mine_local_sid()
            return self.plane.create(
                tenant=tenant, rule=rule, height=height, width=width,
                seed=seed, density=density, with_board=with_board, sid=sid,
            )
        sid = str(sid)
        shard = shard_of(sid, fed.n_shards)
        owner = fed.owner_of(shard)
        if owner == fed.name:
            return self.plane.create(
                tenant=tenant, rule=rule, height=height, width=width,
                seed=seed, density=density, with_board=with_board, sid=sid,
            )
        try:
            return fed.forward(owner, "create", {
                "sid": sid, "tenant": tenant,
                "rule": rule if isinstance(rule, str) else str(rule),
                "height": int(height), "width": int(width),
                "seed": int(seed), "density": float(density),
            })
        except _Moved:
            return self.plane.create(
                tenant=tenant, rule=rule, height=height, width=width,
                seed=seed, density=density, with_board=with_board, sid=sid,
            )

    def get(self, sid: str) -> dict:
        fed = self.fed
        shard = shard_of(str(sid), fed.n_shards)
        owner = fed.owner_of(shard)
        if owner == fed.name:
            return self.plane.get(sid)
        # The one op whose answer carries the board: 307 to the owner
        # instead of hauling O(h·w) cells through a middleman frontend.
        raise FederationRedirect(fed.redirect_url(owner, str(sid)))

    def step(self, sid: str, steps: int = 1) -> Tuple[int, int]:
        fed = self.fed
        shard = shard_of(str(sid), fed.n_shards)
        owner = fed.owner_of(shard)
        if owner == fed.name:
            return self.plane.step(sid, steps)
        try:
            value = fed.forward(
                owner, "step", {"sid": str(sid), "steps": int(steps)}
            )
        except _Moved:
            return self.plane.step(sid, steps)
        return int(value[0]), int(value[1])

    def delete(self, sid: str) -> None:
        fed = self.fed
        shard = shard_of(str(sid), fed.n_shards)
        owner = fed.owner_of(shard)
        if owner == fed.name:
            self.plane.delete(sid)
            return
        try:
            fed.forward(owner, "delete", {"sid": str(sid)})
        except _Moved:
            self.plane.delete(sid)

    def list(self) -> List[dict]:
        # Each frontend lists its own slice of the keyspace (operators
        # aggregate across /boards endpoints; a cluster-wide list would
        # be a fan-out fat payload, exactly what forwarding avoids).
        return self.plane.list()

    def tenant_of(self, sid: str) -> Optional[str]:
        return self.plane.tenant_of(sid)

    def stats(self) -> dict:
        return self.plane.stats()
