"""Worker half of the cluster-sharded serving plane.

A :class:`ServeWorkerPlane` turns one :class:`runtime.backend.BackendWorker`
into a serving shard host: it owns a local :class:`serve.sessions.SessionRouter`
(PR 7's vmapped batch engine, unchanged as the per-worker core) and speaks
the serve wire protocol with the frontend:

- ``SERVE_OPS``  — one frame carrying every op the frontend coalesced for
  this worker (create/step/delete/get, shard ``adopt`` installs, and
  stateless ``step_raw`` tile chunks for frontend-resident mega-board
  sessions).  Ops run on a dedicated executor thread — the control reader
  must never block behind a batch tick.
- ``SERVE_RESULT`` — completions coalesced back: results accumulate while
  a frame is in flight and flush as one frame (the PR 4 discipline, reply
  side).  Step jobs complete asynchronously via the router's ``on_done``
  callback, so a tick's worth of jobs ride one result frame instead of
  parking one thread each.
- ``SHARD_PREPARE`` / ``SHARD_COMMIT`` / ``SHARD_ABORT`` — the worker side
  of a session-shard migration: freeze the named sessions, run their
  admitted jobs dry, export them digest-stamped (``SHARD_STATE``), then
  drop on commit or unfreeze on abort.  Shard control rides the same
  executor queue as ops, so it orders behind every op frame that preceded
  it on the wire.
- ``SHARD_REPLICATE`` / ``SHARD_REPLICATE_ACK`` — session replication:
  a streamer thread exports *dirty* resident sessions (epoch past the
  acked watermark by ``serve_replicate_every``, or new, or idle-dirty)
  at ``serve_replicate_interval_s`` cadence and ships them to the
  frontend, which relays each shard's payloads to its replica worker as
  a ``replicate`` op and acks this primary with the per-session epoch
  watermark.  Watermarks only advance on ack, so a dropped frame in
  either direction is retransmitted by the next pass — convergence is
  exact once traffic stops.  The replica side is the ``replicate`` /
  ``promote`` / ``replica_drop`` ops below: standby payloads live in a
  plain dict OUTSIDE the router (they must not pollute shard-hash freeze
  sets or session listings) until a promotion certifies and installs
  them.

- ``TILED_HALO`` / ``TILED_HALO_ACK`` — worker-resident tiled sessions:
  a mega-board session's halo-padded chunks are installed ONCE
  (``tiled_install``) and stay resident here across steps; each barrier
  round the frontend sends one ``tiled_step`` op per worker and the
  workers exchange O(perimeter) edge strips directly, worker-to-worker,
  over the peer data plane.  Received halo frames ride THIS plane's op
  FIFO (the backend's peer reader enqueues them), so a strip can never
  reorder against the install/step/migration ops of its session.  The
  sender keeps every strip in a retransmit buffer until the receiver's
  ack clears it — a dropped frame stalls a round for one timeout, never
  corrupts it (a round only steps when all 8 strips for a chunk at the
  barrier epoch are in hand).

The plane is constructed from the WELCOME policy bundle (the frontend owns
the ``serve_*`` knobs cluster-wide, exactly like the ring/retry policy).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from akka_game_of_life_tpu.obs import get_registry
from akka_game_of_life_tpu.obs.tracing import TRACE_KEY, get_tracer
from akka_game_of_life_tpu.ops import digest as odigest
from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.wire import pack_tile, unpack_tile
from akka_game_of_life_tpu.serve.sessions import (
    AdmissionError,
    SessionRouter,
    shard_of,
)

# WELCOME policy keys the worker adopts into its local router config —
# the cluster's serve knobs have ONE source of truth, the frontend's
# SimulationConfig (the local caps are only the backstop behind the
# frontend's cluster-wide admission budget).
SERVE_POLICY_KEYS = (
    "serve_shards",
    "serve_max_sessions",
    "serve_max_cells",
    "serve_queue_depth",
    "serve_max_steps",
    "serve_tick_s",
    "serve_ttl_s",
    "serve_size_classes",
    "serve_replicate",
    "serve_replicate_every",
    "serve_replicate_interval_s",
    "serve_tiled_resident",
    "serve_tiled_resident_snapshot",
    "serve_tiled_resident_halo_timeout_s",
    "serve_trace",
    "serve_memo",
    "serve_memo_block",
    "serve_memo_max_mb",
    "serve_memo_hit_floor",
    "serve_memo_warmup",
    "serve_memo_disable_after",
    "serve_memo_certify_every",
    "ff_enabled",
    "ff_certify_steps",
)

# The 8 Moore directions a chunk's halo ring decomposes into, as (dy, dx)
# seen FROM the receiving chunk (its neighbor at chunk-grid offset
# (dy, dx) owns that part of the ring).
_HALO_DIRS = tuple(
    (dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1) if (dy, dx) != (0, 0)
)

# Retransmit attempts per halo strip before the sender gives up loudly
# (the round then stalls until the frontend's barrier timeout resolves
# the session — promotion or failure, never silent corruption).
_HALO_MAX_TRIES = 6

# Snapshot-history depth cap per resident chunk: the certified floor
# normally prunes history to 1-2 entries; the cap only bounds a parked
# or badly lagging stream.
_SNAP_CAP = 8

# A snapshot streamed but not yet acked is not re-sent until the ack
# timeout passes (the ack may simply be in flight); after it, the next
# pass retransmits — the loss-recovery half of the watermark protocol.
# Scaled with the stream interval, floored here.
REPL_ACK_TIMEOUT_FLOOR_S = 0.5


def serve_policy(config) -> Dict[str, object]:
    """The WELCOME ``serve`` bundle from the frontend's config.

    ``serve_ttl_s`` ships as 0: in cluster mode the FRONTEND owns the TTL
    sweep (it must — it charges the cluster admission budget, and a
    worker evicting locally would leak that budget forever since nothing
    reports evictions upstream).  The frontend sweep issues real delete
    ops, so worker tables and the cluster index retire together."""
    policy = {k: getattr(config, k) for k in SERVE_POLICY_KEYS}
    policy["serve_ttl_s"] = 0.0
    return policy


import functools


@functools.lru_cache(maxsize=None)
def _batched_step_fn(rule, n_steps: int):
    """One jitted vmapped n-steps-per-call closure over a [B, H, W]
    chunk stack — a worker advances ALL its ready chunks of a round in
    one device dispatch (cached per (rule, n); jit specializes per stack
    shape, and the caller pads B to a power of two so the compile count
    stays O(log chunks))."""
    import jax

    from akka_game_of_life_tpu.ops import stencil

    @jax.jit
    def run(stack):
        return jax.vmap(
            lambda s: stencil.multi_step(s, rule, n_steps)
        )(stack)

    from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost

    return registered_jit(
        "serve_tiled", (str(rule), n_steps), run,
        cost=lambda stack: stencil_cost(
            stack.shape[-2], stack.shape[-1], n_steps, boards=stack.shape[0]
        ),
    )


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _chunk_key(cy: int, cx: int) -> str:
    """Wire spelling of a chunk id (dict keys must be strings)."""
    return f"{cy},{cx}"


def _parse_chunk(key) -> tuple:
    if isinstance(key, str):
        cy, cx = key.split(",")
        return (int(cy), int(cx))
    return (int(key[0]), int(key[1]))


class _Chunk:
    """One resident tiled-session chunk: the live board plus its snapshot
    history (the rollback/replication source).  Executor-thread owned;
    only the ``snaps`` dict is shared with the replication streamer
    (mutated under the plane lock)."""

    __slots__ = (
        "sid", "cy", "cx", "gy", "gx", "th", "tw", "ny", "nx",
        "H", "W", "rule_s", "rule", "k", "board", "epoch", "pop",
        "snaps",
    )

    def __init__(self, sid, cy, cx, gy, gx, th, tw, ny, nx, H, W,
                 rule_s, k, board, epoch):
        from akka_game_of_life_tpu.ops.rules import resolve_rule

        self.sid = sid
        self.cy, self.cx = cy, cx
        self.gy, self.gx = gy, gx
        self.th, self.tw = th, tw
        self.ny, self.nx = ny, nx
        self.H, self.W = H, W
        self.rule_s = rule_s
        self.rule = resolve_rule(rule_s)
        self.k = k
        self.board = board
        self.epoch = epoch
        self.pop = int((board == 1).sum())
        # epoch -> self-contained snapshot payload (wire shape), pruned
        # by the frontend-relayed certified floor.
        self.snaps: Dict[int, dict] = {}

    def retain(self, pay: dict) -> None:
        """Retain one snapshot payload (caller holds the plane lock).
        The depth cap THROTTLES instead of evicting: when the history is
        full (certified floor stuck — replica lagging or parked), new
        snapshots are simply not retained until floor pruning frees
        room.  Evicting the oldest would silently delete the very
        barrier the certified-resume contract promises to restore."""
        epoch = int(pay["epoch"])
        if epoch in self.snaps or len(self.snaps) < _SNAP_CAP:
            self.snaps[epoch] = pay

    def payload(self, epoch: int, state: dict, lanes, pop: int) -> dict:
        """A self-contained wire payload for this chunk at ``epoch`` —
        replication, export, and promotion all speak this one shape."""
        return {
            "sid": self.sid,
            "chunk": [self.cy, self.cx],
            "origin": [self.gy, self.gx],
            "shape": [self.th, self.tw],
            "width": self.W,
            "epoch": int(epoch),
            "state": state,
            "digest": [int(lanes[0]), int(lanes[1])],
            "pop": int(pop),
        }

    def lanes(self, board=None):
        board = self.board if board is None else board
        return odigest.digest_dense_np(
            board, origin=(self.gy, self.gx), width=self.W
        )


class _Round:
    """One in-flight halo round on this worker: the listed chunks step
    from ``epoch`` by ``ks[0]`` once every strip at ``epoch`` is in hand.
    A multi-round request (``len(ks) > 1``) CHAINS worker-side — the
    next round registers and its strips go out the moment this one's
    chunks land, with no frontend involvement until the last round's
    result (executor-thread owned)."""

    __slots__ = (
        "rid", "sid", "epoch", "ks", "chunks", "all_chunks", "need",
        "digest", "snap_epochs", "owners", "halo_bytes", "lanes",
        "pops", "started",
    )

    def __init__(self, rid, sid, epoch, ks, chunks, digest, snap_epochs,
                 owners, now):
        self.rid = rid
        self.sid = sid
        self.epoch = epoch
        self.ks = ks  # per-round step counts; ks[0] is THIS round's
        self.chunks = list(chunks)  # still to step this round
        self.all_chunks = tuple(chunks)
        # (cy, cx) -> {(dy, dx): strip} collected for this round
        self.need: Dict[tuple, Dict[tuple, np.ndarray]] = {
            c: {} for c in chunks
        }
        self.digest = digest
        self.snap_epochs = snap_epochs  # absolute epochs to snapshot at
        self.owners = owners
        self.halo_bytes = 0
        self.lanes: Dict[str, list] = {}
        self.pops: Dict[str, int] = {}
        self.started = now

    @property
    def k(self) -> int:
        return self.ks[0]

    def next_round(self, now: float) -> "_Round":
        return _Round(
            self.rid, self.sid, self.epoch + self.ks[0], self.ks[1:],
            self.all_chunks, self.digest, self.snap_epochs, self.owners,
            now,
        )


def _err_entry(rid: int, e: BaseException) -> dict:
    """One failed op as a wire result entry; the frontend re-raises the
    matching exception class at the tenant-facing surface."""
    if isinstance(e, AdmissionError):
        return {"rid": rid, "err": "admission", "reason": e.reason,
                "detail": str(e)}
    kind = {
        KeyError: "key",
        ValueError: "value",
        TypeError: "value",
        TimeoutError: "timeout",
    }.get(type(e), "runtime")
    detail = e.args[0] if kind == "key" and e.args else str(e)
    return {"rid": rid, "err": kind, "detail": str(detail)}


class ServeWorkerPlane:
    """One worker's serving engine + its wire glue.  Thread layout: an
    executor thread runs ops/shard control in arrival order; a reply
    thread coalesces completed results into SERVE_RESULT frames; batch
    step completions arrive via router callbacks."""

    def __init__(
        self,
        policy: Dict[str, object],
        send,
        *,
        name: str = "",
        registry=None,
        tracer=None,
        peer_send=None,
    ) -> None:
        from akka_game_of_life_tpu.runtime.config import SimulationConfig

        cfg = SimulationConfig(
            **{k: policy[k] for k in SERVE_POLICY_KEYS if k in policy}
        )
        self.name = name
        self._send = send  # callable(msg) -> None; raises OSError when dead
        # callable(name, host, port, msg): queue a frame onto the named
        # peer's async send lane (the backend's _PeerSender — never blocks
        # the executor on a wedged link).  None = loopback-only (tests).
        self._peer_send = peer_send
        self.metrics = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.router = SessionRouter(
            cfg, registry=self.metrics, tracer=self.tracer
        )
        self.n_shards = int(cfg.serve_shards)
        # Per-request tracing (serve_trace through the WELCOME bundle):
        # when an op carries frontend trace ctx, its execution becomes a
        # serve.batch span under the originating serve.request.
        self._trace = bool(getattr(cfg, "serve_trace", True))
        # shard → the sid set THIS worker froze at prepare (executor-thread
        # only, so unlocked): commit/abort without explicit sids act on it.
        self._shard_frozen: Dict[int, List[str]] = {}
        # Replica half: shard → {sid: wire payload} standby copies, kept
        # OUTSIDE the router so they never pollute shard-hash freeze sets,
        # listings, or the local admission backstop (executor-thread only,
        # like _shard_frozen).
        self._standby: Dict[int, Dict[str, dict]] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._inbox: deque = deque()  # graftlint: guarded-by _lock
        self._results: List[dict] = []  # graftlint: guarded-by _lock
        self._stopped = False  # graftlint: guarded-by _lock
        # Primary half of replication: per-session watermark state (acked
        # epoch, last streamed epoch/time, last pass's epoch for the
        # idle-flush rule) and the shard park set (no replica placeable —
        # the frontend parks the stream instead of letting this worker
        # re-ship every board every pass in single-copy mode).
        self._repl_state: Dict[str, dict] = {}  # graftlint: guarded-by _lock
        self._repl_parked: set = set()  # graftlint: guarded-by _lock
        self.replicate = bool(cfg.serve_replicate)
        self._repl_interval_s = float(cfg.serve_replicate_interval_s)
        self._repl_every = int(cfg.serve_replicate_every)
        self._ack_timeout_s = max(
            REPL_ACK_TIMEOUT_FLOOR_S, 4 * self._repl_interval_s
        )
        # -- worker-resident tiled sessions ---------------------------------
        self._halo_timeout_s = float(cfg.serve_tiled_resident_halo_timeout_s)
        # (sid, (cy, cx)) -> _Chunk: the resident store (executor-thread
        # only, like _shard_frozen; the replication streamer reads chunk
        # snapshot payloads under self._lock via _tiled_repl).
        self._resident: Dict[tuple, _Chunk] = {}
        # (sid, epoch) -> _Round awaiting halos (executor only).
        self._rounds: Dict[tuple, _Round] = {}
        # Early strips: (sid, (cy,cx), epoch, (dy,dx)) -> (strip, t_seen).
        self._halo_buf: Dict[tuple, tuple] = {}
        # Unacked outgoing strips for retransmit: key -> record.
        self._halo_out: Dict[tuple, dict] = {}
        self._halo_upkeep_t = 0.0
        # Replica half: sid -> {(cy,cx) -> {epoch -> payload}} standby
        # snapshot history (executor only).
        self._tiled_standby: Dict[str, Dict[tuple, Dict[int, dict]]] = {}
        # Primary half: (sid, (cy,cx)) -> watermark record; the chunk's
        # snaps dict is mutated by the executor and read by the repl
        # streamer, both under self._lock.
        self._tiled_repl: Dict[tuple, dict] = {}  # graftlint: guarded-by _lock
        self._tiled_parked: set = set()  # graftlint: guarded-by _lock
        self._m_resident = self.metrics.gauge(
            "gol_serve_tiled_resident_chunks"
        )
        self._m_halo_bytes = self.metrics.counter(
            "gol_serve_tiled_halo_bytes_total"
        )
        self._m_halo_retx = self.metrics.counter(
            "gol_serve_tiled_halo_retx_total"
        )
        self._exec = threading.Thread(
            target=self._exec_loop, daemon=True, name=f"serve-exec-{name}"
        )
        self._reply = threading.Thread(
            target=self._reply_loop, daemon=True, name=f"serve-reply-{name}"
        )
        self._exec.start()
        self._reply.start()
        if self.replicate:
            self._repl = threading.Thread(
                target=self._repl_loop, daemon=True,
                name=f"serve-repl-{name}",
            )
            self._repl.start()

    # -- wire-in (called from the worker's control reader thread) ------------

    def handle(self, msg: dict) -> None:
        """Enqueue one serve-plane control message; never blocks."""
        with self._lock:
            if self._stopped:
                return
            self._inbox.append(msg)
            self._work.notify_all()

    def has_sessions(self) -> bool:
        return self.router.stats()["sessions"] > 0

    def home_summary(self) -> dict:
        """The ``SHARD_HOME`` payload a re-homed control channel announces
        to its adopting frontend after a frontend loss: every session this
        worker hosts (the router's list — id/tenant/rule/epoch/digest per
        row), which IS the truth that closes the federation failover
        window (docs/OPERATIONS.md "Frontend scale-out & HA")."""
        return {"sessions": self.router.list()}

    # -- executor -------------------------------------------------------------

    def _exec_loop(self) -> None:
        import time

        while True:
            with self._lock:
                while not self._stopped and not self._inbox:
                    self._work.wait(timeout=0.2)
                    if self._halo_out or self._halo_buf:
                        break
                if self._stopped:
                    return
                msg = self._inbox.popleft() if self._inbox else None
            try:
                if msg is not None:
                    kind = msg.get("type")
                    if kind == P.SERVE_OPS:
                        for op in msg.get("ops", []):
                            self._run_op(op)
                    elif kind == P.SHARD_PREPARE:
                        self._on_prepare(msg)
                    elif kind == P.SHARD_COMMIT:
                        self.router.drop_sessions(self._shard_sids(msg))
                    elif kind == P.SHARD_ABORT:
                        self.router.unfreeze_sessions(self._shard_sids(msg))
                    elif kind == P.SHARD_REPLICATE_ACK:
                        self._on_replicate_ack(msg)
                    elif kind == P.TILED_HALO:
                        self._on_tiled_halo(msg)
                    elif kind == P.TILED_HALO_ACK:
                        self._halo_out.pop(
                            (str(msg["sid"]), int(msg["epoch"]),
                             str(msg.get("from", ""))),
                            None,
                        )
                self._halo_upkeep(time.monotonic())
            except Exception as e:  # noqa: BLE001 — one bad frame must not
                # kill the executor: every op answers, malformed ones loudly
                print(f"serve plane: dropped bad frame: {e!r}", flush=True)

    def _run_op(self, op: dict) -> None:
        rid = int(op["rid"])
        kind = op.get("op")
        ctx = op.get(TRACE_KEY)  # the originating serve.request's ctx
        if not isinstance(ctx, dict):
            ctx = None
        try:
            if kind == "create":
                doc = self.router.create(
                    tenant=str(op.get("tenant", "default")),
                    rule=op.get("rule", "conway"),
                    height=int(op.get("height", 64)),
                    width=int(op.get("width", 64)),
                    seed=int(op.get("seed", 0)),
                    density=float(op.get("density", 0.5)),
                    with_board=False,
                    sid=str(op["sid"]),
                )
                self._push({"rid": rid, "ok": 1, "doc": doc})
            elif kind == "step":
                # Async: the job's on_done callback pushes the result when
                # its batch lands — the executor moves straight on to the
                # next op, so every step of a frame rides the same tick.
                # With trace ctx riding the op, the whole execution (queue
                # wait + its slice of the vmapped batch) is a serve.batch
                # span under the originating serve.request, and the result
                # entry echoes the ctx back across the serve_result frame.
                span = None
                if self._trace and ctx is not None:
                    span = self.tracer.start(
                        "serve.batch",
                        parent=ctx,
                        node=self.name or None,
                        sid=str(op["sid"]),
                        steps=int(op.get("steps", 1)),
                    )

                def _step_done(job, rid=rid, span=span, ctx=ctx):
                    qw = job.queue_wait_s if job.t_enq else None
                    if span is not None:
                        span.set(
                            outcome="error" if job.error is not None
                            else "ok"
                        )
                        if qw is not None:
                            span.set(queue_wait_s=round(qw, 6))
                        span.finish()
                    if job.error is not None:
                        entry = _err_entry(rid, job.error)
                    else:
                        entry = {
                            "rid": rid,
                            "ok": 1,
                            "epoch": job.result[0],
                            "digest": job.result[1],
                        }
                        if qw is not None:
                            entry["qw"] = round(qw, 6)
                    if ctx is not None:
                        entry[TRACE_KEY] = ctx
                    self._push(entry)

                try:
                    self.router.submit(
                        str(op["sid"]),
                        int(op.get("steps", 1)),
                        on_done=_step_done,
                    )
                except BaseException:
                    if span is not None:
                        # Refused at admission: the job never existed, so
                        # the callback will never fire — close the span
                        # here and let the outer handler answer the op.
                        span.set(outcome="rejected")
                        span.finish()
                    raise
            elif kind == "get":
                self._push(
                    {"rid": rid, "ok": 1, "doc": self.router.get(str(op["sid"]))}
                )
            elif kind == "delete":
                self.router.delete(str(op["sid"]))
                self._push({"rid": rid, "ok": 1})
            elif kind == "adopt":
                self.router.import_sessions(op["sessions"])
                self._push({"rid": rid, "ok": 1})
            elif kind == "replicate":
                self._push(self._replicate_op(rid, op))
            elif kind == "promote":
                self._push(self._promote_op(rid, op))
            elif kind == "replica_drop":
                self._standby.pop(int(op["shard"]), None)
                self._push({"rid": rid, "ok": 1})
            elif kind == "step_raw":
                self._push(self._step_raw(rid, op))
            elif kind == "tiled_install":
                self._push(self._tiled_install(rid, op))
            elif kind == "tiled_step":
                self._tiled_step(rid, op)  # async: pushes when the round completes
            elif kind == "tiled_fetch":
                self._push(self._tiled_fetch(rid, op))
            elif kind == "tiled_export":
                self._push(self._tiled_export(rid, op))
            elif kind == "tiled_adopt":
                self._push(self._tiled_adopt(rid, op))
            elif kind == "tiled_drop":
                self._tiled_drop(str(op["sid"]), None)
                self._push({"rid": rid, "ok": 1})
            elif kind == "tiled_chunk_drop":
                self._tiled_drop(
                    str(op["sid"]),
                    [_parse_chunk(c) for c in op.get("chunks", [])],
                )
                self._push({"rid": rid, "ok": 1})
            elif kind == "tiled_replicate":
                self._push(self._tiled_replicate(rid, op))
            elif kind == "tiled_promote":
                self._push(self._tiled_promote(rid, op))
            elif kind == "tiled_rollback":
                self._push(self._tiled_rollback(rid, op))
            elif kind == "tiled_replica_drop":
                sid = str(op["sid"])
                chunks = op.get("chunks")
                if chunks is None:
                    self._tiled_standby.pop(sid, None)
                else:
                    store = self._tiled_standby.get(sid, {})
                    for c in chunks:
                        store.pop(_parse_chunk(c), None)
                    if not store:
                        self._tiled_standby.pop(sid, None)
                self._push({"rid": rid, "ok": 1})
            else:
                raise ValueError(f"unknown serve op {kind!r}")
        except BaseException as e:  # noqa: BLE001 — answered, never dropped
            self._push(_err_entry(rid, e))

    def _step_raw(self, rid: int, op: dict) -> dict:
        """A stateless tile chunk of a frontend-resident tiled (mega-board)
        session: step the k-halo-padded slab k epochs (halo absorbs the
        padded-torus wrap contamination, so the interior is exactly the
        global evolution), return the interior packed plus its digest
        lanes at the tile's global offsets."""
        import jax.numpy as jnp

        from akka_game_of_life_tpu.ops import stencil
        from akka_game_of_life_tpu.ops.rules import resolve_rule

        rule = resolve_rule(op["rule"])
        k = int(op["k"])
        padded = unpack_tile(op["state"])
        out = np.asarray(stencil.multi_step_fn(rule, k)(jnp.asarray(padded)))
        y0, y1, x0, x1 = (int(v) for v in op["interior"])
        interior = np.ascontiguousarray(out[y0:y1, x0:x1])
        lanes = odigest.digest_dense_np(
            interior,
            origin=tuple(int(v) for v in op["origin"]),
            width=int(op["width"]),
        )
        return {
            "rid": rid,
            "ok": 1,
            "state": pack_tile(interior),
            "digest": [int(lanes[0]), int(lanes[1])],
        }

    # -- worker-resident tiled sessions (docs/OPERATIONS.md) ------------------

    def _resident_gauge(self) -> None:
        self._m_resident.set(len(self._resident))

    def _tiled_install(self, rid: int, op: dict) -> dict:
        """Install one resident chunk (create/adopt both land here via
        payload shape).  The install epoch counts as a snapshot barrier:
        the chunk can be promoted from its replica the moment the epoch-0
        stream acks."""
        sid = str(op["sid"])
        cy, cx = _parse_chunk(op["chunk"])
        gy, gx = (int(v) for v in op["origin"])
        th, tw = (int(v) for v in op["shape"])
        ny, nx = (int(v) for v in op["grid"])
        chunk = _Chunk(
            sid, cy, cx, gy, gx, th, tw, ny, nx,
            int(op["H"]), int(op["W"]), str(op["rule"]), int(op["k"]),
            unpack_tile(op["state"]), int(op.get("epoch", 0)),
        )
        self._resident[(sid, (cy, cx))] = chunk
        self._resident_gauge()
        if op.get("replicate", True) and self.replicate:
            self._tiled_snapshot(chunk)
        return {"rid": rid, "ok": 1}

    def _tiled_snapshot(self, chunk: _Chunk) -> None:
        """Retain a snapshot of the chunk at its CURRENT epoch — the
        local rollback source and the replication stream's next payload."""
        lanes = chunk.lanes()
        pay = chunk.payload(
            chunk.epoch, pack_tile(chunk.board), lanes, chunk.pop
        )
        key = (chunk.sid, (chunk.cy, chunk.cx))
        with self._lock:
            chunk.retain(pay)
            self._tiled_repl.setdefault(
                key, {"acked": -1, "sent": -1, "sent_t": 0.0}
            )

    def _strip_for(self, chunk: _Chunk, dy: int, dx: int) -> np.ndarray:
        """The part of this chunk's board a neighbor's halo ring needs,
        for ring direction (dy, dx) as seen FROM the receiver (this chunk
        sits at receiver + (dy, dx) on the torus chunk grid)."""
        k = chunk.k
        rows = {
            -1: slice(chunk.th - k, chunk.th), 0: slice(None),
            1: slice(0, k),
        }[dy]
        cols = {
            -1: slice(chunk.tw - k, chunk.tw), 0: slice(None),
            1: slice(0, k),
        }[dx]
        return np.ascontiguousarray(chunk.board[rows, cols])

    def _send_strips(self, rnd: _Round, owners: Dict[str, list],
                     now: float) -> None:
        """Cut every listed chunk's 8 edge strips at the round's barrier
        epoch and push them: loopback strips deliver straight into the
        local buffer; remote strips COALESCE into one TILED_HALO frame
        per destination worker (the PR 4 discipline — per-strip frames
        cost more in per-frame overhead than the strips themselves),
        each batch with one retransmit record cleared by one ack."""
        sid, E = rnd.sid, rnd.epoch
        me = None
        batches: Dict[str, Tuple[list, List[dict]]] = {}
        for c in rnd.chunks:
            chunk = self._resident[(sid, c)]
            if me is None:
                me = owners.get(_chunk_key(chunk.cy, chunk.cx))
            for dy, dx in _HALO_DIRS:
                rcy = (chunk.cy - dy) % chunk.ny
                rcx = (chunk.cx - dx) % chunk.nx
                strip = self._strip_for(chunk, dy, dx)
                dest = owners.get(_chunk_key(rcy, rcx))
                if dest is None:
                    continue
                if dest[0] == self.name or self._peer_send is None:
                    self._halo_buf[(sid, (rcy, rcx), E, (dy, dx))] = (
                        strip, now
                    )
                    continue
                entry = batches.setdefault(dest[0], (dest, [], []))
                entry[1].append({
                    "chunk": [rcy, rcx], "dir": [dy, dx],
                    "shape": list(strip.shape),
                })
                entry[2].append(strip.reshape(-1))
        for name, (dest, metas, flats) in batches.items():
            # One flat buffer, ONE vectorized packbits per frame (the
            # PR 4 ring-codec discipline, strip edition): per-strip
            # pack_tile calls cost more CPU than the 8x byte saving is
            # worth, a single batched pack costs neither.  Multi-state
            # rules ride raw uint8.
            flat = (
                flats[0] if len(flats) == 1 else np.concatenate(flats)
            )
            binary = bool(
                self._resident[(sid, rnd.all_chunks[0])].rule.is_binary
            )
            data = np.packbits(flat) if binary else flat
            msg = {
                "type": P.TILED_HALO, "sid": sid, "epoch": E,
                "meta": metas, "data": data, "n": int(flat.size),
                "enc": "bits1" if binary else "raw", "src": me,
            }
            rnd.halo_bytes += int(data.nbytes)
            self._m_halo_bytes.inc(int(data.nbytes))
            self._halo_out[(sid, E, name)] = {
                "msg": msg, "dest": dest, "t": now, "tries": 1,
            }
            self._peer_send(dest[0], dest[1], int(dest[2]), msg)

    def _on_tiled_halo(self, msg: dict) -> None:
        """A peer's strip batch arrived (via the peer reader, through
        this plane's op FIFO): ack the batch, buffer every strip, and
        step anything they complete."""
        import time

        sid = str(msg["sid"])
        E = int(msg["epoch"])
        src = msg.get("src")
        if src and self._peer_send is not None and src[0] != self.name:
            self._peer_send(src[0], src[1], int(src[2]), {
                "type": P.TILED_HALO_ACK, "sid": sid, "epoch": E,
                "from": self.name,
            })
        now = time.monotonic()
        n = int(msg.get("n", 0))
        data = np.asarray(msg["data"], dtype=np.uint8).reshape(-1)
        flat = (
            np.unpackbits(data, count=n)
            if msg.get("enc") == "bits1" else data
        )
        off = 0
        for meta in msg.get("meta", []):
            h, w = (int(v) for v in meta["shape"])
            key = (
                sid, _parse_chunk(meta["chunk"]), E,
                (int(meta["dir"][0]), int(meta["dir"][1])),
            )
            self._halo_buf[key] = (
                flat[off:off + h * w].reshape(h, w), now
            )
            off += h * w
        self._feed_rounds(sid)

    def _halo_upkeep(self, now: float) -> None:
        """Periodic executor pass: retransmit unacked strips past the ack
        timeout, prune stale buffers, fail rounds that can never finish."""
        if now - self._halo_upkeep_t < min(0.2, self._halo_timeout_s):
            return
        self._halo_upkeep_t = now
        for key, rec in list(self._halo_out.items()):
            if now - rec["t"] < self._halo_timeout_s:
                continue
            if rec["tries"] >= _HALO_MAX_TRIES:
                del self._halo_out[key]
                print(
                    f"serve tiled: halo strip {key} unacked after "
                    f"{rec['tries']} sends; giving up",
                    flush=True,
                )
                continue
            rec["tries"] += 1
            rec["t"] = now
            self._m_halo_retx.inc()
            dest = rec["dest"]
            if self._peer_send is not None:
                self._peer_send(dest[0], dest[1], int(dest[2]), rec["msg"])
        for key, (_, seen) in list(self._halo_buf.items()):
            if now - seen > 60.0:
                del self._halo_buf[key]

    def _tiled_step(self, rid: int, op: dict) -> None:
        """One barrier round for this worker's chunks of a tiled session:
        send our strips, register the round, and step as halos land.  The
        result pushes asynchronously when the last chunk steps — the
        executor keeps draining the FIFO meanwhile (the frames that
        complete this round arrive through it)."""
        import time

        sid = str(op["sid"])
        E = int(op["epoch"])
        ks = [int(v) for v in op["ks"]]
        owners = dict(op.get("owners", {}))
        chunks = [_parse_chunk(c) for c in op["chunks"]]
        floor = int(op.get("floor", -1))
        now = time.monotonic()
        for c in chunks:
            chunk = self._resident.get((sid, c))
            if chunk is None:
                raise KeyError(f"{sid}:{c} not resident here")
            if chunk.epoch != E or max(ks) > chunk.k:
                # Strips are always chunk.k wide, so any round of k <=
                # chunk.k epochs is exact; an epoch mismatch means the
                # frontend and this worker disagree about the session
                # state (a cancelled round, a stale op) — fail loudly.
                raise RuntimeError(
                    f"tiled chunk {sid}:{c} at epoch {chunk.epoch} "
                    f"(k={chunk.k}), request asked {E} ks={ks}"
                )
            if floor >= 0:
                self._prune_snaps(chunk, floor)
        rnd = _Round(
            rid, sid, E, ks, chunks,
            bool(op.get("digest", True)),
            frozenset(int(v) for v in op.get("snap_epochs", [])),
            owners, now,
        )
        self._rounds[(sid, E)] = rnd
        self._send_strips(rnd, owners, now)
        self._feed_rounds(sid)

    def _prune_snaps(self, chunk: _Chunk, floor: int) -> None:
        """Drop snapshot history below the session's certified floor —
        but never the newest snapshot (the stream may still need it)."""
        with self._lock:
            for e in [e for e in chunk.snaps if e < floor]:
                if e != max(chunk.snaps):
                    del chunk.snaps[e]

    def _feed_rounds(self, sid: str) -> None:
        """Move buffered strips into this session's active rounds and
        step every chunk whose halo ring is complete — all ready chunks
        of a round advance in ONE batched device call (a per-chunk jit
        dispatch costs more than a 272² step; residency means the worker
        sees its whole chunk set at once, so it can batch where the
        ship-per-round path's independent ops cannot)."""
        import time

        pending = [
            key for key in self._rounds if key[0] == sid
        ]
        while pending:
            key = pending.pop()
            rnd = self._rounds.get(key)
            if rnd is None:
                continue
            E = rnd.epoch
            ready = []
            for c in list(rnd.chunks):
                got = rnd.need[c]
                for d in _HALO_DIRS:
                    if d in got:
                        continue
                    hit = self._halo_buf.pop((sid, c, E, d), None)
                    if hit is not None:
                        got[d] = hit[0]
                if len(got) == len(_HALO_DIRS):
                    ready.append(c)
            if ready:
                self._step_chunks(rnd, ready)
            if rnd.chunks:
                continue
            del self._rounds[key]
            if len(rnd.ks) > 1:
                # Chain the request's next round HERE, worker-side: its
                # strips go out now and it may already be steppable from
                # buffered fast-peer strips — the frontend is not in the
                # loop again until the last round's result.
                now = time.monotonic()
                nxt = rnd.next_round(now)
                nxt.halo_bytes = rnd.halo_bytes
                self._rounds[(sid, nxt.epoch)] = nxt
                self._send_strips(nxt, nxt.owners, now)
                pending.append((sid, nxt.epoch))
                continue
            entry = {
                "rid": rnd.rid, "ok": 1, "epoch": E + rnd.k,
                "halo_bytes": rnd.halo_bytes,
            }
            if rnd.digest:
                entry["lanes"] = rnd.lanes
                entry["pop"] = rnd.pops
            self._push(entry)

    def _step_chunks(self, rnd: _Round, ready: List[tuple]) -> None:
        """Advance the ready chunks k epochs in one batched device call
        per (shape, pad) group: assemble the halo-padded slabs, stack
        them (batch padded to a power of two so the compile count stays
        O(log chunks)), run the vmapped multi-step kernel once, commit
        the interiors as the new resident state."""
        groups: Dict[tuple, List[tuple]] = {}
        for c in ready:
            chunk = self._resident[(rnd.sid, c)]
            groups.setdefault(
                (chunk.th, chunk.tw, chunk.k), []
            ).append(c)
        for (th, tw, k), cs in groups.items():
            rows = {-1: slice(0, k), 0: slice(k, k + th),
                    1: slice(k + th, k + th + k)}
            cols = {-1: slice(0, k), 0: slice(k, k + tw),
                    1: slice(k + tw, k + tw + k)}
            first = self._resident[(rnd.sid, cs[0])]
            stack = np.empty(
                (_next_pow2(len(cs)), th + 2 * k, tw + 2 * k),
                dtype=np.uint8,
            )
            for i, c in enumerate(cs):
                chunk = self._resident[(rnd.sid, c)]
                padded = stack[i]
                padded[k:k + th, k:k + tw] = chunk.board
                for (dy, dx), strip in rnd.need[c].items():
                    padded[rows[dy], cols[dx]] = strip
            for i in range(len(cs), stack.shape[0]):
                stack[i] = stack[0]  # pow2 pad: dead lanes, never read
            # The round may advance fewer epochs than the halo is wide
            # (rnd.k <= chunk.k): the interior at offset k is exact for
            # any step count up to the pad width.
            out = np.asarray(
                _batched_step_fn(first.rule, rnd.k)(stack)
            )
            final = len(rnd.ks) == 1
            for i, c in enumerate(cs):
                chunk = self._resident[(rnd.sid, c)]
                chunk.board = np.ascontiguousarray(
                    out[i, k:k + th, k:k + tw]
                )
                chunk.epoch += rnd.k
                rnd.chunks.remove(c)
                snapshot = (
                    self.replicate and chunk.epoch in rnd.snap_epochs
                )
                if (final and rnd.digest) or snapshot:
                    lanes = chunk.lanes()
                    chunk.pop = int((chunk.board == 1).sum())
                    if final and rnd.digest:
                        rnd.lanes[_chunk_key(*c)] = [
                            int(lanes[0]), int(lanes[1])
                        ]
                        rnd.pops[_chunk_key(*c)] = chunk.pop
                    if snapshot:
                        pay = chunk.payload(
                            chunk.epoch, pack_tile(chunk.board), lanes,
                            chunk.pop,
                        )
                        with self._lock:
                            chunk.retain(pay)

    def _tiled_fetch(self, rid: int, op: dict) -> dict:
        """Render pull: the session's resident chunk states, packed (only
        on GET ?with_board=1 — the steady-state path never ships these)."""
        sid = str(op["sid"])
        states = []
        for c in (_parse_chunk(c) for c in op["chunks"]):
            chunk = self._resident.get((sid, c))
            if chunk is None:
                raise KeyError(f"{sid}:{c} not resident here")
            states.append({
                "chunk": list(c), "origin": [chunk.gy, chunk.gx],
                "shape": [chunk.th, chunk.tw], "epoch": chunk.epoch,
                "state": pack_tile(chunk.board),
                "pop": int((chunk.board == 1).sum()),
            })
        return {"rid": rid, "ok": 1, "states": states}

    def _tiled_export(self, rid: int, op: dict) -> dict:
        """Migration TRANSFER: the chunk's live state digest-stamped plus
        its retained snapshot history (the dest must be able to roll back
        to the session's certified floor, exactly like the source)."""
        sid = str(op["sid"])
        out = []
        for c in (_parse_chunk(c) for c in op["chunks"]):
            chunk = self._resident.get((sid, c))
            if chunk is None:
                raise KeyError(f"{sid}:{c} not resident here")
            lanes = chunk.lanes()
            pay = chunk.payload(
                chunk.epoch, pack_tile(chunk.board), lanes,
                int((chunk.board == 1).sum()),
            )
            with self._lock:
                pay["snaps"] = [
                    chunk.snaps[e] for e in sorted(chunk.snaps)
                ]
            out.append(pay)
        return {"rid": rid, "ok": 1, "chunks": out}

    def _tiled_adopt(self, rid: int, op: dict) -> dict:
        """Migration install at the destination: certified payloads (the
        frontend re-derived every digest) become resident chunks, snapshot
        history included; the replication stream restarts from scratch."""
        sid = str(op["sid"])
        meta = op["meta"]
        for pay in op["chunks"]:
            cy, cx = _parse_chunk(pay["chunk"])
            gy, gx = (int(v) for v in pay["origin"])
            th, tw = (int(v) for v in pay["shape"])
            chunk = _Chunk(
                sid, cy, cx, gy, gx, th, tw,
                int(meta["grid"][0]), int(meta["grid"][1]),
                int(meta["H"]), int(meta["W"]), str(meta["rule"]),
                int(meta["k"]), unpack_tile(pay["state"]),
                int(pay["epoch"]),
            )
            self._resident[(sid, (cy, cx))] = chunk
            with self._lock:
                for snap in pay.get("snaps", []):
                    chunk.snaps[int(snap["epoch"])] = snap
                self._tiled_repl[(sid, (cy, cx))] = {
                    "acked": -1, "sent": -1, "sent_t": 0.0,
                }
        self._resident_gauge()
        return {"rid": rid, "ok": 1}

    def _tiled_drop(self, sid: str, chunks) -> None:
        """Release resident chunks (session delete/evict, or the source
        half of a committed chunk migration) and every per-chunk buffer
        that addressed them."""
        keys = [
            key for key in self._resident
            if key[0] == sid and (chunks is None or key[1] in chunks)
        ]
        for key in keys:
            del self._resident[key]
            with self._lock:
                self._tiled_repl.pop(key, None)
        if chunks is None:
            for rk in [k for k in self._rounds if k[0] == sid]:
                rnd = self._rounds.pop(rk)
                self._push(_err_entry(
                    rnd.rid, RuntimeError(f"session {sid} dropped mid-round")
                ))
            for bk in [k for k in self._halo_buf if k[0] == sid]:
                del self._halo_buf[bk]
            for ok_ in [k for k in self._halo_out if k[0] == sid]:
                del self._halo_out[ok_]
            # A full-session drop also retires any standby history this
            # worker replicates for the session — a worker is routinely
            # BOTH an owner and a replica of the same session, and the
            # frontend sends it one cleanup op, not two.
            self._tiled_standby.pop(sid, None)
            with self._lock:
                self._tiled_parked.discard(sid)
        self._resident_gauge()

    def _tiled_replicate(self, rid: int, op: dict) -> dict:
        """Replica half: store standby snapshot payloads (history, pruned
        by the certified floor the frontend relays) and ack the newest
        epoch held per chunk — the watermark the frontend records."""
        sid = str(op["sid"])
        floor = int(op.get("floor", -1))
        store = self._tiled_standby.setdefault(sid, {})
        acked: Dict[str, int] = {}
        for pay in op.get("chunks", []):
            c = _parse_chunk(pay["chunk"])
            hist = store.setdefault(c, {})
            hist[int(pay["epoch"])] = pay
            for e in [e for e in hist if e < floor and e != max(hist)]:
                del hist[e]
            while len(hist) > 4 * _SNAP_CAP:
                # Backstop only: the primary throttles at _SNAP_CAP, so a
                # healthy stream never gets here; evict loudly, never
                # silently (the evicted barrier can no longer promote).
                e = min(hist)
                del hist[e]
                print(
                    f"serve tiled: standby history overflow, evicting "
                    f"epoch {e} of {pay.get('sid')}:{c}",
                    flush=True,
                )
            acked[_chunk_key(*c)] = max(hist)
        return {"rid": rid, "ok": 1, "sid": sid, "acked": acked}

    def _tiled_promote(self, rid: int, op: dict) -> dict:
        """Worker-loss failover, resident-chunk edition: certify the
        standby payloads at the session's certified epoch and install
        them as resident chunks — this worker owns them from here on."""
        sid = str(op["sid"])
        C = int(op["epoch"])
        meta = op["meta"]
        store = self._tiled_standby.get(sid, {})
        installed: List[dict] = []
        failed: List[list] = []
        for c in (_parse_chunk(c) for c in op["chunks"]):
            pay = store.get(c, {}).get(C)
            if pay is None:
                failed.append(list(c))
                continue
            lanes = odigest.digest_payload_np(
                pay["state"],
                tuple(int(v) for v in pay["origin"]),
                int(pay["width"]),
            )
            if [int(lanes[0]), int(lanes[1])] != [
                int(v) for v in pay["digest"]
            ]:
                failed.append(list(c))
                continue
            cy, cx = c
            gy, gx = (int(v) for v in pay["origin"])
            th, tw = (int(v) for v in pay["shape"])
            chunk = _Chunk(
                sid, cy, cx, gy, gx, th, tw,
                int(meta["grid"][0]), int(meta["grid"][1]),
                int(meta["H"]), int(meta["W"]), str(meta["rule"]),
                int(meta["k"]), unpack_tile(pay["state"]), C,
            )
            self._resident[(sid, c)] = chunk
            with self._lock:
                chunk.snaps[C] = pay
                self._tiled_repl[(sid, c)] = {
                    "acked": -1, "sent": -1, "sent_t": 0.0,
                }
            store.pop(c, None)
            installed.append({
                "chunk": list(c), "epoch": C,
                "digest": [int(v) for v in pay["digest"]],
                "pop": int(pay.get("pop", 0)),
            })
        if not store:
            self._tiled_standby.pop(sid, None)
        self._resident_gauge()
        return {
            "rid": rid, "ok": 1, "sid": sid,
            "installed": installed, "failed": failed,
        }

    def _tiled_rollback(self, rid: int, op: dict) -> dict:
        """Survivor half of a tiled promotion: revert this worker's
        resident chunks of the session to their local snapshot at the
        certified epoch, cancel any stalled round (its halos died with
        the worker), and report the restored per-chunk digests."""
        sid = str(op["sid"])
        C = int(op["epoch"])
        for rk in [k for k in self._rounds if k[0] == sid]:
            rnd = self._rounds.pop(rk)
            self._push(_err_entry(
                rnd.rid,
                RuntimeError(f"round at {rk[1]} cancelled by rollback"),
            ))
        for bk in [k for k in self._halo_buf if k[0] == sid]:
            del self._halo_buf[bk]
        for ok_ in [k for k in self._halo_out if k[0] == sid]:
            del self._halo_out[ok_]
        restored: List[dict] = []
        missing: List[list] = []
        for (rsid, c), chunk in list(self._resident.items()):
            if rsid != sid:
                continue
            with self._lock:
                pay = chunk.snaps.get(C)
                if pay is not None:
                    for e in [e for e in chunk.snaps if e > C]:
                        del chunk.snaps[e]
            if pay is None:
                missing.append(list(c))
                continue
            chunk.board = unpack_tile(pay["state"])
            chunk.epoch = C
            chunk.pop = int(pay.get("pop", 0))
            restored.append({
                "chunk": list(c), "epoch": C,
                "digest": [int(v) for v in pay["digest"]],
                "pop": chunk.pop,
            })
        return {
            "rid": rid, "ok": 1, "sid": sid,
            "restored": restored, "missing": missing,
        }

    # -- shard migration (worker side) ---------------------------------------

    def _shard_sids(self, msg: dict) -> List[str]:
        """The sid set a commit/abort acts on: the frontend's explicit
        list when present (a commit carries the exact exported set; the
        ghost-cleanup drop at a destination names adopted sids), else the
        set THIS worker froze at prepare."""
        shard = int(msg["shard"])
        remembered = self._shard_frozen.pop(shard, [])
        if "sids" in msg:
            return [str(s) for s in msg["sids"]]
        return remembered

    def _on_prepare(self, msg: dict) -> None:
        """Freeze → run admitted jobs dry → export digest-stamped.  The
        freeze set is computed HERE, by hash over the sessions actually
        resident when the prepare executes — the executor has already run
        every op frame that preceded it on the wire, so a create routed
        before the migration was planned is included; a frontend snapshot
        could not promise that.  A freeze that cannot go idle in time
        reports the failure instead of exporting a snapshot an in-flight
        write-back could invalidate."""
        shard = int(msg["shard"])
        seq = int(msg["seq"])
        sids = [
            doc["id"]
            for doc in self.router.list()
            if shard_of(doc["id"], self.n_shards) == shard
        ]
        self._shard_frozen[shard] = sids
        self.router.freeze_sessions(sids)
        reply: dict = {"type": P.SHARD_STATE, "shard": shard, "seq": seq}
        if not self.router.wait_idle(sids):
            # Unfreeze here too: the frontend will abort, but its abort
            # frame could race a crash — never leave sessions frozen on a
            # failure the worker itself detected.
            self.router.unfreeze_sessions(sids)
            reply["error"] = "freeze timeout (jobs still in flight)"
            reply["sessions"] = []
        else:
            reply["sessions"] = self.router.export_sessions(sids)
        try:
            self._send(reply)
        except (OSError, ValueError):
            # Dead control channel: the worker is leaving anyway; the
            # frontend's member-loss path owns the outcome.
            self.router.unfreeze_sessions(sids)

    # -- session replication (replica half: standby install + promotion) -----

    def _replicate_op(self, rid: int, op: dict) -> dict:
        """Install/refresh standby copies for one shard (idempotent —
        re-delivered frames after a lost ack just overwrite), drop
        deleted sids, and ack each installed session's epoch — the
        watermark the frontend records and relays to the primary."""
        shard = int(op["shard"])
        store = self._standby.setdefault(shard, {})
        acked: Dict[str, int] = {}
        for pay in op.get("sessions", []):
            sid = str(pay["sid"])
            cur = store.get(sid)
            if cur is None or int(pay["epoch"]) >= int(cur["epoch"]):
                # Never step a standby copy BACKWARD: a reordered/
                # retransmitted older snapshot must not undo a newer one.
                store[sid] = pay
            acked[sid] = int(store[sid]["epoch"])
        for sid in op.get("deleted", []):
            store.pop(str(sid), None)
        if not store:
            self._standby.pop(shard, None)
        return {"rid": rid, "ok": 1, "shard": shard, "acked": acked}

    def _promote_op(self, rid: int, op: dict) -> dict:
        """Worker loss failover: certify this shard's standby payloads
        against their streamed digest lanes and install the good ones
        into the router — this worker is the shard's primary from here
        on.  A corrupt payload is refused per-session (reported in
        ``failed``), never installed with a wrong digest."""
        shard = int(op["shard"])
        store = self._standby.pop(shard, {})
        good: List[dict] = []
        installed: List[dict] = []
        failed: List[str] = []
        for sid, pay in sorted(store.items()):
            lanes = odigest.digest_payload_np(
                pay["state"], (0, 0), int(pay["width"])
            )
            if [int(lanes[0]), int(lanes[1])] == [
                int(v) for v in pay["digest"]
            ]:
                good.append(pay)
            else:
                failed.append(sid)
        self.router.import_sessions(good)
        for pay in good:
            installed.append({
                "sid": pay["sid"],
                "epoch": int(pay["epoch"]),
                "digest": [int(v) for v in pay["digest"]],
            })
        return {
            "rid": rid, "ok": 1, "shard": shard,
            "installed": installed, "failed": failed,
        }

    # -- session replication (primary half: the watermark stream) ------------

    def _on_replicate_ack(self, msg: dict) -> None:
        """The frontend's watermark/park/reset frame, on the op FIFO."""
        shard = int(msg["shard"])
        with self._lock:
            if msg.get("reset"):
                # Replica reassigned (loss, drain re-home, promotion):
                # everything the OLD replica acked is gone — stream the
                # shard from scratch.
                self._repl_parked.discard(shard)
                for sid in list(self._repl_state):
                    if shard_of(sid, self.n_shards) == shard:
                        del self._repl_state[sid]
                return
            if msg.get("parked"):
                # No replica placeable (single-copy mode): stop paying
                # bandwidth for a stream nobody stores; a reset unparks.
                self._repl_parked.add(shard)
                return
            for sid, epoch in dict(msg.get("acked", {})).items():
                st = self._repl_state.get(str(sid))
                if st is not None:
                    st["acked"] = max(st["acked"], int(epoch))
            # Resident tiled chunks share the frame: per-chunk snapshot
            # watermarks, the certified floor (prunes local history), and
            # per-session park/reset arms.
            for sid, by_chunk in dict(msg.get("tiled_acked", {})).items():
                for ck, epoch in dict(by_chunk).items():
                    st = self._tiled_repl.get((str(sid), _parse_chunk(ck)))
                    if st is not None:
                        st["acked"] = max(st["acked"], int(epoch))
            for sid in msg.get("tiled_parked", []):
                self._tiled_parked.add(str(sid))
            for sid, chunks in dict(msg.get("tiled_reset", {})).items():
                self._tiled_parked.discard(str(sid))
                for ck in chunks:
                    st = self._tiled_repl.get((str(sid), _parse_chunk(ck)))
                    if st is not None:
                        st.update(acked=-1, sent=-1, sent_t=0.0)

    def _repl_loop(self) -> None:
        """The primary's stream pass: every interval, export sessions
        dirty past the watermark (cadence-due, never-acked, or idle —
        unchanged since the last pass, so convergence is exact once
        traffic stops) and ship them grouped per shard.  Watermarks only
        advance on ack; anything unacked past REPL_ACK_TIMEOUT_S
        retransmits."""
        import time

        while True:
            with self._lock:
                if self._stopped:
                    return
            time.sleep(self._repl_interval_s)
            try:
                by_shard = self._repl_pass(time.monotonic())
            except Exception as e:  # noqa: BLE001 — replication is a
                # background best-effort stream; a pass failure must never
                # kill the thread (the next pass retransmits)
                print(f"serve replication pass failed: {e!r}", flush=True)
                continue
            for shard, sessions in sorted(by_shard.items()):
                try:
                    self._send({
                        "type": P.SHARD_REPLICATE,
                        "shard": shard,
                        "sessions": sessions,
                    })
                except (OSError, ValueError):
                    return  # dead control channel: the worker is leaving
            tiled = self._tiled_repl_pass(time.monotonic())
            if tiled:
                try:
                    self._send({"type": P.SHARD_REPLICATE, "tiled": tiled})
                except (OSError, ValueError):
                    return

    def _repl_pass(self, now: float) -> Dict[int, List[dict]]:
        """One pass: pick the dirty-and-due sids, export, mark sent."""
        docs = self.router.list()
        with self._lock:
            live = {d["id"] for d in docs}
            for sid in list(self._repl_state):
                if sid not in live:
                    del self._repl_state[sid]
            due: List[str] = []
            for doc in docs:
                sid, epoch = doc["id"], int(doc["epoch"])
                shard = shard_of(sid, self.n_shards)
                st = self._repl_state.setdefault(
                    sid, {"acked": -1, "sent": -1, "sent_t": 0.0, "seen": -1}
                )
                seen, st["seen"] = st["seen"], epoch
                if shard in self._repl_parked or epoch <= st["acked"]:
                    continue
                cadence_due = (
                    st["acked"] < 0
                    or epoch - st["acked"] >= self._repl_every
                    or epoch == seen  # idle flush: dirty, not advancing
                )
                awaiting = (
                    st["sent"] >= epoch
                    and now - st["sent_t"] < self._ack_timeout_s
                )
                if cadence_due and not awaiting:
                    due.append(sid)
                    st["sent"] = epoch
                    st["sent_t"] = now
        by_shard: Dict[int, List[dict]] = {}
        for pay in self.router.export_sessions(due):
            by_shard.setdefault(
                shard_of(pay["sid"], self.n_shards), []
            ).append(pay)
        return by_shard

    def _tiled_repl_pass(self, now: float) -> List[dict]:
        """The resident-chunk half of a stream pass: ship every snapshot
        past the acked watermark (oldest first, so acks advance in
        barrier order), honoring the per-session park set and the same
        ack-timeout retransmit contract as sessions."""
        out: List[dict] = []
        with self._lock:
            for (sid, c), st in self._tiled_repl.items():
                if sid in self._tiled_parked:
                    continue
                chunk = self._resident.get((sid, c))
                if chunk is None:
                    continue
                due = sorted(e for e in chunk.snaps if e > st["acked"])
                if not due:
                    continue
                if (
                    st["sent"] >= due[-1]
                    and now - st["sent_t"] < self._ack_timeout_s
                ):
                    continue
                out.extend(chunk.snaps[e] for e in due)
                st["sent"] = due[-1]
                st["sent_t"] = now
        return out

    # -- reply coalescer ------------------------------------------------------

    def _push(self, entry: dict) -> None:
        with self._lock:
            if self._stopped:
                return
            self._results.append(entry)
            self._work.notify_all()

    def _reply_loop(self) -> None:
        while True:
            with self._lock:
                while not self._stopped and not self._results:
                    self._work.wait(timeout=0.25)
                if self._stopped:
                    return
                batch, self._results = self._results, []
            # One frame per flush: results that accumulate while this
            # send is on the wire coalesce into the next frame.
            try:
                self._send({"type": P.SERVE_RESULT, "results": batch})
            except (OSError, ValueError):
                # Dead control channel — nothing to answer to; the
                # frontend's member-loss path fails the in-flight ops.
                return

    def close(self) -> None:
        with self._lock:
            self._stopped = True
            self._work.notify_all()
        self.router.close()
