"""Worker half of the cluster-sharded serving plane.

A :class:`ServeWorkerPlane` turns one :class:`runtime.backend.BackendWorker`
into a serving shard host: it owns a local :class:`serve.sessions.SessionRouter`
(PR 7's vmapped batch engine, unchanged as the per-worker core) and speaks
the serve wire protocol with the frontend:

- ``SERVE_OPS``  — one frame carrying every op the frontend coalesced for
  this worker (create/step/delete/get, shard ``adopt`` installs, and
  stateless ``step_raw`` tile chunks for frontend-resident mega-board
  sessions).  Ops run on a dedicated executor thread — the control reader
  must never block behind a batch tick.
- ``SERVE_RESULT`` — completions coalesced back: results accumulate while
  a frame is in flight and flush as one frame (the PR 4 discipline, reply
  side).  Step jobs complete asynchronously via the router's ``on_done``
  callback, so a tick's worth of jobs ride one result frame instead of
  parking one thread each.
- ``SHARD_PREPARE`` / ``SHARD_COMMIT`` / ``SHARD_ABORT`` — the worker side
  of a session-shard migration: freeze the named sessions, run their
  admitted jobs dry, export them digest-stamped (``SHARD_STATE``), then
  drop on commit or unfreeze on abort.  Shard control rides the same
  executor queue as ops, so it orders behind every op frame that preceded
  it on the wire.
- ``SHARD_REPLICATE`` / ``SHARD_REPLICATE_ACK`` — session replication:
  a streamer thread exports *dirty* resident sessions (epoch past the
  acked watermark by ``serve_replicate_every``, or new, or idle-dirty)
  at ``serve_replicate_interval_s`` cadence and ships them to the
  frontend, which relays each shard's payloads to its replica worker as
  a ``replicate`` op and acks this primary with the per-session epoch
  watermark.  Watermarks only advance on ack, so a dropped frame in
  either direction is retransmitted by the next pass — convergence is
  exact once traffic stops.  The replica side is the ``replicate`` /
  ``promote`` / ``replica_drop`` ops below: standby payloads live in a
  plain dict OUTSIDE the router (they must not pollute shard-hash freeze
  sets or session listings) until a promotion certifies and installs
  them.

The plane is constructed from the WELCOME policy bundle (the frontend owns
the ``serve_*`` knobs cluster-wide, exactly like the ring/retry policy).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from akka_game_of_life_tpu.obs import get_registry
from akka_game_of_life_tpu.obs.tracing import get_tracer
from akka_game_of_life_tpu.ops import digest as odigest
from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.wire import pack_tile, unpack_tile
from akka_game_of_life_tpu.serve.sessions import (
    AdmissionError,
    SessionRouter,
    shard_of,
)

# WELCOME policy keys the worker adopts into its local router config —
# the cluster's serve knobs have ONE source of truth, the frontend's
# SimulationConfig (the local caps are only the backstop behind the
# frontend's cluster-wide admission budget).
SERVE_POLICY_KEYS = (
    "serve_shards",
    "serve_max_sessions",
    "serve_max_cells",
    "serve_queue_depth",
    "serve_max_steps",
    "serve_tick_s",
    "serve_ttl_s",
    "serve_size_classes",
    "serve_replicate",
    "serve_replicate_every",
    "serve_replicate_interval_s",
    "ff_enabled",
    "ff_certify_steps",
)

# A snapshot streamed but not yet acked is not re-sent until the ack
# timeout passes (the ack may simply be in flight); after it, the next
# pass retransmits — the loss-recovery half of the watermark protocol.
# Scaled with the stream interval, floored here.
REPL_ACK_TIMEOUT_FLOOR_S = 0.5


def serve_policy(config) -> Dict[str, object]:
    """The WELCOME ``serve`` bundle from the frontend's config.

    ``serve_ttl_s`` ships as 0: in cluster mode the FRONTEND owns the TTL
    sweep (it must — it charges the cluster admission budget, and a
    worker evicting locally would leak that budget forever since nothing
    reports evictions upstream).  The frontend sweep issues real delete
    ops, so worker tables and the cluster index retire together."""
    policy = {k: getattr(config, k) for k in SERVE_POLICY_KEYS}
    policy["serve_ttl_s"] = 0.0
    return policy


def _err_entry(rid: int, e: BaseException) -> dict:
    """One failed op as a wire result entry; the frontend re-raises the
    matching exception class at the tenant-facing surface."""
    if isinstance(e, AdmissionError):
        return {"rid": rid, "err": "admission", "reason": e.reason,
                "detail": str(e)}
    kind = {
        KeyError: "key",
        ValueError: "value",
        TypeError: "value",
        TimeoutError: "timeout",
    }.get(type(e), "runtime")
    detail = e.args[0] if kind == "key" and e.args else str(e)
    return {"rid": rid, "err": kind, "detail": str(detail)}


class ServeWorkerPlane:
    """One worker's serving engine + its wire glue.  Thread layout: an
    executor thread runs ops/shard control in arrival order; a reply
    thread coalesces completed results into SERVE_RESULT frames; batch
    step completions arrive via router callbacks."""

    def __init__(
        self,
        policy: Dict[str, object],
        send,
        *,
        name: str = "",
        registry=None,
        tracer=None,
    ) -> None:
        from akka_game_of_life_tpu.runtime.config import SimulationConfig

        cfg = SimulationConfig(
            **{k: policy[k] for k in SERVE_POLICY_KEYS if k in policy}
        )
        self.name = name
        self._send = send  # callable(msg) -> None; raises OSError when dead
        self.metrics = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.router = SessionRouter(
            cfg, registry=self.metrics, tracer=self.tracer
        )
        self.n_shards = int(cfg.serve_shards)
        # shard → the sid set THIS worker froze at prepare (executor-thread
        # only, so unlocked): commit/abort without explicit sids act on it.
        self._shard_frozen: Dict[int, List[str]] = {}
        # Replica half: shard → {sid: wire payload} standby copies, kept
        # OUTSIDE the router so they never pollute shard-hash freeze sets,
        # listings, or the local admission backstop (executor-thread only,
        # like _shard_frozen).
        self._standby: Dict[int, Dict[str, dict]] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._inbox: deque = deque()  # graftlint: guarded-by _lock
        self._results: List[dict] = []  # graftlint: guarded-by _lock
        self._stopped = False  # graftlint: guarded-by _lock
        # Primary half of replication: per-session watermark state (acked
        # epoch, last streamed epoch/time, last pass's epoch for the
        # idle-flush rule) and the shard park set (no replica placeable —
        # the frontend parks the stream instead of letting this worker
        # re-ship every board every pass in single-copy mode).
        self._repl_state: Dict[str, dict] = {}  # graftlint: guarded-by _lock
        self._repl_parked: set = set()  # graftlint: guarded-by _lock
        self.replicate = bool(cfg.serve_replicate)
        self._repl_interval_s = float(cfg.serve_replicate_interval_s)
        self._repl_every = int(cfg.serve_replicate_every)
        self._ack_timeout_s = max(
            REPL_ACK_TIMEOUT_FLOOR_S, 4 * self._repl_interval_s
        )
        self._exec = threading.Thread(
            target=self._exec_loop, daemon=True, name=f"serve-exec-{name}"
        )
        self._reply = threading.Thread(
            target=self._reply_loop, daemon=True, name=f"serve-reply-{name}"
        )
        self._exec.start()
        self._reply.start()
        if self.replicate:
            self._repl = threading.Thread(
                target=self._repl_loop, daemon=True,
                name=f"serve-repl-{name}",
            )
            self._repl.start()

    # -- wire-in (called from the worker's control reader thread) ------------

    def handle(self, msg: dict) -> None:
        """Enqueue one serve-plane control message; never blocks."""
        with self._lock:
            if self._stopped:
                return
            self._inbox.append(msg)
            self._work.notify_all()

    def has_sessions(self) -> bool:
        return self.router.stats()["sessions"] > 0

    # -- executor -------------------------------------------------------------

    def _exec_loop(self) -> None:
        while True:
            with self._lock:
                while not self._stopped and not self._inbox:
                    self._work.wait(timeout=0.25)
                if self._stopped:
                    return
                msg = self._inbox.popleft()
            try:
                kind = msg.get("type")
                if kind == P.SERVE_OPS:
                    for op in msg.get("ops", []):
                        self._run_op(op)
                elif kind == P.SHARD_PREPARE:
                    self._on_prepare(msg)
                elif kind == P.SHARD_COMMIT:
                    self.router.drop_sessions(self._shard_sids(msg))
                elif kind == P.SHARD_ABORT:
                    self.router.unfreeze_sessions(self._shard_sids(msg))
                elif kind == P.SHARD_REPLICATE_ACK:
                    self._on_replicate_ack(msg)
            except Exception as e:  # noqa: BLE001 — one bad frame must not
                # kill the executor: every op answers, malformed ones loudly
                print(f"serve plane: dropped bad frame: {e!r}", flush=True)

    def _run_op(self, op: dict) -> None:
        rid = int(op["rid"])
        kind = op.get("op")
        try:
            if kind == "create":
                doc = self.router.create(
                    tenant=str(op.get("tenant", "default")),
                    rule=op.get("rule", "conway"),
                    height=int(op.get("height", 64)),
                    width=int(op.get("width", 64)),
                    seed=int(op.get("seed", 0)),
                    density=float(op.get("density", 0.5)),
                    with_board=False,
                    sid=str(op["sid"]),
                )
                self._push({"rid": rid, "ok": 1, "doc": doc})
            elif kind == "step":
                # Async: the job's on_done callback pushes the result when
                # its batch lands — the executor moves straight on to the
                # next op, so every step of a frame rides the same tick.
                self.router.submit(
                    str(op["sid"]),
                    int(op.get("steps", 1)),
                    on_done=lambda job, rid=rid: self._push(
                        _err_entry(rid, job.error)
                        if job.error is not None
                        else {
                            "rid": rid,
                            "ok": 1,
                            "epoch": job.result[0],
                            "digest": job.result[1],
                        }
                    ),
                )
            elif kind == "get":
                self._push(
                    {"rid": rid, "ok": 1, "doc": self.router.get(str(op["sid"]))}
                )
            elif kind == "delete":
                self.router.delete(str(op["sid"]))
                self._push({"rid": rid, "ok": 1})
            elif kind == "adopt":
                self.router.import_sessions(op["sessions"])
                self._push({"rid": rid, "ok": 1})
            elif kind == "replicate":
                self._push(self._replicate_op(rid, op))
            elif kind == "promote":
                self._push(self._promote_op(rid, op))
            elif kind == "replica_drop":
                self._standby.pop(int(op["shard"]), None)
                self._push({"rid": rid, "ok": 1})
            elif kind == "step_raw":
                self._push(self._step_raw(rid, op))
            else:
                raise ValueError(f"unknown serve op {kind!r}")
        except BaseException as e:  # noqa: BLE001 — answered, never dropped
            self._push(_err_entry(rid, e))

    def _step_raw(self, rid: int, op: dict) -> dict:
        """A stateless tile chunk of a frontend-resident tiled (mega-board)
        session: step the k-halo-padded slab k epochs (halo absorbs the
        padded-torus wrap contamination, so the interior is exactly the
        global evolution), return the interior packed plus its digest
        lanes at the tile's global offsets."""
        import jax.numpy as jnp

        from akka_game_of_life_tpu.ops import stencil
        from akka_game_of_life_tpu.ops.rules import resolve_rule

        rule = resolve_rule(op["rule"])
        k = int(op["k"])
        padded = unpack_tile(op["state"])
        out = np.asarray(stencil.multi_step_fn(rule, k)(jnp.asarray(padded)))
        y0, y1, x0, x1 = (int(v) for v in op["interior"])
        interior = np.ascontiguousarray(out[y0:y1, x0:x1])
        lanes = odigest.digest_dense_np(
            interior,
            origin=tuple(int(v) for v in op["origin"]),
            width=int(op["width"]),
        )
        return {
            "rid": rid,
            "ok": 1,
            "state": pack_tile(interior),
            "digest": [int(lanes[0]), int(lanes[1])],
        }

    # -- shard migration (worker side) ---------------------------------------

    def _shard_sids(self, msg: dict) -> List[str]:
        """The sid set a commit/abort acts on: the frontend's explicit
        list when present (a commit carries the exact exported set; the
        ghost-cleanup drop at a destination names adopted sids), else the
        set THIS worker froze at prepare."""
        shard = int(msg["shard"])
        remembered = self._shard_frozen.pop(shard, [])
        if "sids" in msg:
            return [str(s) for s in msg["sids"]]
        return remembered

    def _on_prepare(self, msg: dict) -> None:
        """Freeze → run admitted jobs dry → export digest-stamped.  The
        freeze set is computed HERE, by hash over the sessions actually
        resident when the prepare executes — the executor has already run
        every op frame that preceded it on the wire, so a create routed
        before the migration was planned is included; a frontend snapshot
        could not promise that.  A freeze that cannot go idle in time
        reports the failure instead of exporting a snapshot an in-flight
        write-back could invalidate."""
        shard = int(msg["shard"])
        seq = int(msg["seq"])
        sids = [
            doc["id"]
            for doc in self.router.list()
            if shard_of(doc["id"], self.n_shards) == shard
        ]
        self._shard_frozen[shard] = sids
        self.router.freeze_sessions(sids)
        reply: dict = {"type": P.SHARD_STATE, "shard": shard, "seq": seq}
        if not self.router.wait_idle(sids):
            # Unfreeze here too: the frontend will abort, but its abort
            # frame could race a crash — never leave sessions frozen on a
            # failure the worker itself detected.
            self.router.unfreeze_sessions(sids)
            reply["error"] = "freeze timeout (jobs still in flight)"
            reply["sessions"] = []
        else:
            reply["sessions"] = self.router.export_sessions(sids)
        try:
            self._send(reply)
        except (OSError, ValueError):
            # Dead control channel: the worker is leaving anyway; the
            # frontend's member-loss path owns the outcome.
            self.router.unfreeze_sessions(sids)

    # -- session replication (replica half: standby install + promotion) -----

    def _replicate_op(self, rid: int, op: dict) -> dict:
        """Install/refresh standby copies for one shard (idempotent —
        re-delivered frames after a lost ack just overwrite), drop
        deleted sids, and ack each installed session's epoch — the
        watermark the frontend records and relays to the primary."""
        shard = int(op["shard"])
        store = self._standby.setdefault(shard, {})
        acked: Dict[str, int] = {}
        for pay in op.get("sessions", []):
            sid = str(pay["sid"])
            cur = store.get(sid)
            if cur is None or int(pay["epoch"]) >= int(cur["epoch"]):
                # Never step a standby copy BACKWARD: a reordered/
                # retransmitted older snapshot must not undo a newer one.
                store[sid] = pay
            acked[sid] = int(store[sid]["epoch"])
        for sid in op.get("deleted", []):
            store.pop(str(sid), None)
        if not store:
            self._standby.pop(shard, None)
        return {"rid": rid, "ok": 1, "shard": shard, "acked": acked}

    def _promote_op(self, rid: int, op: dict) -> dict:
        """Worker loss failover: certify this shard's standby payloads
        against their streamed digest lanes and install the good ones
        into the router — this worker is the shard's primary from here
        on.  A corrupt payload is refused per-session (reported in
        ``failed``), never installed with a wrong digest."""
        shard = int(op["shard"])
        store = self._standby.pop(shard, {})
        good: List[dict] = []
        installed: List[dict] = []
        failed: List[str] = []
        for sid, pay in sorted(store.items()):
            lanes = odigest.digest_payload_np(
                pay["state"], (0, 0), int(pay["width"])
            )
            if [int(lanes[0]), int(lanes[1])] == [
                int(v) for v in pay["digest"]
            ]:
                good.append(pay)
            else:
                failed.append(sid)
        self.router.import_sessions(good)
        for pay in good:
            installed.append({
                "sid": pay["sid"],
                "epoch": int(pay["epoch"]),
                "digest": [int(v) for v in pay["digest"]],
            })
        return {
            "rid": rid, "ok": 1, "shard": shard,
            "installed": installed, "failed": failed,
        }

    # -- session replication (primary half: the watermark stream) ------------

    def _on_replicate_ack(self, msg: dict) -> None:
        """The frontend's watermark/park/reset frame, on the op FIFO."""
        shard = int(msg["shard"])
        with self._lock:
            if msg.get("reset"):
                # Replica reassigned (loss, drain re-home, promotion):
                # everything the OLD replica acked is gone — stream the
                # shard from scratch.
                self._repl_parked.discard(shard)
                for sid in list(self._repl_state):
                    if shard_of(sid, self.n_shards) == shard:
                        del self._repl_state[sid]
                return
            if msg.get("parked"):
                # No replica placeable (single-copy mode): stop paying
                # bandwidth for a stream nobody stores; a reset unparks.
                self._repl_parked.add(shard)
                return
            for sid, epoch in dict(msg.get("acked", {})).items():
                st = self._repl_state.get(str(sid))
                if st is not None:
                    st["acked"] = max(st["acked"], int(epoch))

    def _repl_loop(self) -> None:
        """The primary's stream pass: every interval, export sessions
        dirty past the watermark (cadence-due, never-acked, or idle —
        unchanged since the last pass, so convergence is exact once
        traffic stops) and ship them grouped per shard.  Watermarks only
        advance on ack; anything unacked past REPL_ACK_TIMEOUT_S
        retransmits."""
        import time

        while True:
            with self._lock:
                if self._stopped:
                    return
            time.sleep(self._repl_interval_s)
            try:
                by_shard = self._repl_pass(time.monotonic())
            except Exception as e:  # noqa: BLE001 — replication is a
                # background best-effort stream; a pass failure must never
                # kill the thread (the next pass retransmits)
                print(f"serve replication pass failed: {e!r}", flush=True)
                continue
            for shard, sessions in sorted(by_shard.items()):
                try:
                    self._send({
                        "type": P.SHARD_REPLICATE,
                        "shard": shard,
                        "sessions": sessions,
                    })
                except (OSError, ValueError):
                    return  # dead control channel: the worker is leaving

    def _repl_pass(self, now: float) -> Dict[int, List[dict]]:
        """One pass: pick the dirty-and-due sids, export, mark sent."""
        docs = self.router.list()
        with self._lock:
            live = {d["id"] for d in docs}
            for sid in list(self._repl_state):
                if sid not in live:
                    del self._repl_state[sid]
            due: List[str] = []
            for doc in docs:
                sid, epoch = doc["id"], int(doc["epoch"])
                shard = shard_of(sid, self.n_shards)
                st = self._repl_state.setdefault(
                    sid, {"acked": -1, "sent": -1, "sent_t": 0.0, "seen": -1}
                )
                seen, st["seen"] = st["seen"], epoch
                if shard in self._repl_parked or epoch <= st["acked"]:
                    continue
                cadence_due = (
                    st["acked"] < 0
                    or epoch - st["acked"] >= self._repl_every
                    or epoch == seen  # idle flush: dirty, not advancing
                )
                awaiting = (
                    st["sent"] >= epoch
                    and now - st["sent_t"] < self._ack_timeout_s
                )
                if cadence_due and not awaiting:
                    due.append(sid)
                    st["sent"] = epoch
                    st["sent_t"] = now
        by_shard: Dict[int, List[dict]] = {}
        for pay in self.router.export_sessions(due):
            by_shard.setdefault(
                shard_of(pay["sid"], self.n_shards), []
            ).append(pay)
        return by_shard

    # -- reply coalescer ------------------------------------------------------

    def _push(self, entry: dict) -> None:
        with self._lock:
            if self._stopped:
                return
            self._results.append(entry)
            self._work.notify_all()

    def _reply_loop(self) -> None:
        while True:
            with self._lock:
                while not self._stopped and not self._results:
                    self._work.wait(timeout=0.25)
                if self._stopped:
                    return
                batch, self._results = self._results, []
            # One frame per flush: results that accumulate while this
            # send is on the wire coalesce into the next frame.
            try:
                self._send({"type": P.SERVE_RESULT, "results": batch})
            except (OSError, ValueError):
                # Dead control channel — nothing to answer to; the
                # frontend's member-loss path fails the in-flight ops.
                return

    def close(self) -> None:
        with self._lock:
            self._stopped = True
            self._work.notify_all()
        self.router.close()
