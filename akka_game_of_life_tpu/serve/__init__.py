"""Multi-tenant serving plane: batched boards + session router + HTTP API.

The rest of the runtime simulates ONE board per process; this subsystem
turns it into a *service* — thousands of small per-user boards advancing
in one device program (:mod:`.batch`, the CAX ``vmap``-batched shape with
per-board rule masks as traced data, the CAT "rule as operand" move), a
session table + job queue feeding the engine in ticks with admission
control (:mod:`.sessions`), and ``/boards`` HTTP routes mounted on the
existing obs endpoint (:mod:`.api`).
"""

from akka_game_of_life_tpu.serve.api import board_routes, run_serve
from akka_game_of_life_tpu.serve.batch import (
    DEFAULT_SIZE_CLASSES,
    batch_step_fn,
    size_class,
)
from akka_game_of_life_tpu.serve.sessions import (
    AdmissionError,
    Session,
    SessionRouter,
)

__all__ = [
    "AdmissionError",
    "ClusterServePlane",
    "DEFAULT_SIZE_CLASSES",
    "ServeWorkerPlane",
    "Session",
    "SessionRouter",
    "batch_step_fn",
    "board_routes",
    "run_serve",
    "run_serve_cluster",
    "size_class",
]


def __getattr__(name):
    # The cluster-sharded plane imports runtime.frontend machinery; lazy
    # so `import akka_game_of_life_tpu.serve` stays light for the
    # single-process role.
    if name in ("ClusterServePlane", "run_serve_cluster"):
        from akka_game_of_life_tpu.serve import cluster as _c

        return getattr(_c, name)
    if name == "ServeWorkerPlane":
        from akka_game_of_life_tpu.serve.worker import ServeWorkerPlane

        return ServeWorkerPlane
    raise AttributeError(name)
