"""The ``/boards`` HTTP API: the serving plane's tenant-facing surface.

Mounted on the existing obs endpoint through its registered-routes table
(:meth:`akka_game_of_life_tpu.obs.httpd.MetricsServer.add_route`) — one
port serves ``/metrics``, ``/healthz``, ``/trace``, AND the board API.

| Method & path            | Body (JSON)                               | Returns |
|--------------------------|-------------------------------------------|---------|
| POST /boards             | {tenant?, rule?, height?, width?, seed?, density?} | 201 session doc |
| GET /boards              | —                                         | 200 {boards: [...]} (no cells) |
| GET /boards/<id>         | —                                         | 200 session doc (+ board cells) |
| POST /boards/<id>/step   | {steps?}                                  | 200 {epoch, digest, steps} |
| DELETE /boards/<id>      | —                                         | 200 {deleted} |

``steps`` beyond ``serve_max_steps`` is an admission question: an
XOR-linear rule session answers through the O(log T) fast-forward path
(``ops/fastforward.py`` — n=1,000,000 in milliseconds, bypassing the
ticker), while any other session is refused **429** ``max_steps`` so a
giant request can never monopolize the ticker.

Error mapping — admission control answers, it never wedges: a capacity
refusal (session cap, cell budget, full step queue, shutdown drain,
over-bound steps on a non-linear rule) is
**429** with the machine-readable ``reason`` (the same string on
``gol_serve_rejects_total{reason}``) and a ``Retry-After`` hint in the
body; a step that timed out is **503** (the body says whether it was
cancelled in-queue — board provably not advanced, retry safe); malformed
requests are 400; unknown ids 404; everything else 500 with the error
repr.  Board cells travel as base64 of the raw row-major
uint8 bytes (``board_b64`` + the height/width already in the doc) — JSON-
safe at any state alphabet without a 4-byte-per-cell integer list.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.obs.httpd import JSON_TYPE, json_response
from akka_game_of_life_tpu.serve.sessions import AdmissionError, SessionRouter


def _doc(snapshot: dict, *, with_board: bool = True) -> dict:
    doc = dict(snapshot)
    board = doc.pop("board", None)
    if with_board and board is not None:
        doc["board_b64"] = base64.b64encode(
            np.ascontiguousarray(board).tobytes()
        ).decode("ascii")
    return doc


def decode_board_b64(doc: dict) -> np.ndarray:
    """Client-side twin of the ``board_b64`` encoding (bench/tests)."""
    raw = base64.b64decode(doc["board_b64"])
    return np.frombuffer(raw, dtype=np.uint8).reshape(
        doc["height"], doc["width"]
    )


class BoardsRoute:
    """The ``/boards`` route handler (callable with the httpd route
    contract: ``(method, path, body) -> (status, ctype, bytes)``)."""

    def __init__(self, router: SessionRouter) -> None:
        self.router = router

    def __call__(self, method: str, path: str, body: bytes):
        try:
            return self._dispatch(method, path, body)
        except AdmissionError as e:
            return json_response(
                429,
                {"error": str(e), "reason": e.reason, "retry_after_s": 0.1},
            )
        except KeyError as e:
            return json_response(404, {"error": f"no board {e.args[0]!r}"})
        except (ValueError, TypeError) as e:
            return json_response(400, {"error": str(e)})
        except TimeoutError as e:
            # The router's distinguished outcomes ("cancelled; board not
            # advanced" = a safe retry) ride str(e) — a generic 500 would
            # read as a route bug and lose the retry signal.
            return json_response(
                503, {"error": str(e), "retry_after_s": 1.0}
            )

    def _dispatch(self, method: str, path: str, body: bytes):
        sid, action = self._parse_path(path)
        if sid is None:
            if method == "POST":
                return self._create(body)
            if method == "GET":
                return json_response(200, {"boards": self.router.list()})
            return json_response(405, {"error": f"{method} /boards"})
        if action == "step":
            if method != "POST":
                return json_response(405, {"error": f"{method} {path}"})
            return self._step(sid, body)
        if action is not None:
            raise KeyError(action)
        if method == "GET":
            return json_response(200, _doc(self.router.get(sid)))
        if method == "DELETE":
            self.router.delete(sid)
            return json_response(200, {"deleted": sid})
        return json_response(405, {"error": f"{method} {path}"})

    @staticmethod
    def _parse_path(path: str) -> Tuple[Optional[str], Optional[str]]:
        """"/boards" → (None, None); "/boards/<id>" → (id, None);
        "/boards/<id>/step" → (id, "step")."""
        parts = [p for p in path.split("/") if p]
        if parts[:1] != ["boards"] or len(parts) > 3:
            raise KeyError(path)
        sid = parts[1] if len(parts) > 1 else None
        action = parts[2] if len(parts) > 2 else None
        return sid, action

    @staticmethod
    def _payload(body: bytes) -> dict:
        if not body:
            return {}
        doc = json.loads(body.decode("utf-8"))
        if not isinstance(doc, dict):
            raise ValueError("request body must be a JSON object")
        return doc

    def _create(self, body: bytes):
        doc = self._payload(body)
        allowed = {"tenant", "rule", "height", "width", "seed", "density"}
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"unknown fields: {sorted(unknown)}")
        snap = self.router.create(
            tenant=str(doc.get("tenant", "default")),
            rule=doc.get("rule", "conway"),
            height=int(doc.get("height", 64)),
            width=int(doc.get("width", 64)),
            seed=int(doc.get("seed", 0)),
            density=float(doc.get("density", 0.5)),
            # The 201 deliberately carries no cells; skip the O(h·w) copy.
            with_board=False,
        )
        return json_response(201, _doc(snap, with_board=False))

    def _step(self, sid: str, body: bytes):
        doc = self._payload(body)
        steps = int(doc.get("steps", 1))
        epoch, digest = self.router.step(sid, steps)
        from akka_game_of_life_tpu.ops.digest import format_digest

        return json_response(
            200,
            {"id": sid, "epoch": epoch, "steps": steps,
             "digest": format_digest(digest)},
        )


def board_routes(router: SessionRouter) -> dict:
    """The route table to mount on a MetricsServer (``routes=`` kwarg or
    ``add_route`` per entry)."""
    return {"/boards": BoardsRoute(router)}


def run_serve(config, *, registry=None, tracer=None) -> int:
    """The ``serve`` CLI role body: a SessionRouter + one obs endpoint
    carrying /metrics, /healthz, /trace, and /boards, until interrupted."""
    from akka_game_of_life_tpu.obs import MetricsServer, get_registry
    from akka_game_of_life_tpu.obs.tracing import get_tracer

    registry = registry if registry is not None else get_registry()
    tracer = tracer if tracer is not None else get_tracer()
    router = SessionRouter(config, registry=registry, tracer=tracer)

    def health() -> dict:
        return {"ok": True, "role": "serve", **router.stats()}

    server = MetricsServer(
        registry,
        port=config.metrics_port,
        health=health,
        tracer=tracer,
        routes=board_routes(router),
    )
    print(
        f"serving /boards (+/metrics,/healthz,/trace) on :{server.port} — "
        f"max {router.max_sessions} sessions, {router.max_cells} cells, "
        f"size classes {list(router.size_classes)}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        # A real drain, not just the word: refuse NEW work (429 reason
        # "draining") and run the admitted queue dry before closing — an
        # accepted job is never failed with "router closed" because the
        # operator sent SIGTERM.
        print("serve: interrupted; draining", flush=True)
        drained = router.drain()
        print(
            "serve: drained" if drained
            else "serve: drain timed out; aborting pending jobs",
            flush=True,
        )
        return 130
    finally:
        server.close()
        router.close()
