"""The ``/boards`` HTTP API: the serving plane's tenant-facing surface.

Mounted on the existing obs endpoint through its registered-routes table
(:meth:`akka_game_of_life_tpu.obs.httpd.MetricsServer.add_route`) — one
port serves ``/metrics``, ``/healthz``, ``/trace``, ``/slo``, AND the
board API.

| Method & path            | Body (JSON)                               | Returns |
|--------------------------|-------------------------------------------|---------|
| POST /boards             | {tenant?, rule?, height?, width?, seed?, density?, sid?} | 201 session doc |
| GET /boards              | —                                         | 200 {boards: [...]} (no cells) |
| GET /boards/<id>         | —                                         | 200 session doc (+ board cells) |
| POST /boards/<id>/step   | {steps?}                                  | 200 {epoch, digest, steps} |
| DELETE /boards/<id>      | —                                         | 200 {deleted} |

``steps`` beyond ``serve_max_steps`` is an admission question: an
XOR-linear rule session answers through the O(log T) fast-forward path
(``ops/fastforward.py`` — n=1,000,000 in milliseconds, bypassing the
ticker), while any other session is refused **429** ``max_steps`` so a
giant request can never monopolize the ticker.

Every request is a first-class traced, SLO-scored object
(``serve_trace`` / docs/OPERATIONS.md "Serve observability & SLOs"):
the route mints a ``serve.request`` span — or adopts the trace ctx a
client passed under the ``"_trace"`` body key — leaves it active for the
whole dispatch so every downstream serve-plane span (and, on the cluster
plane, every ``serve_ops`` frame) links under it, and records the
finished request into the :class:`~akka_game_of_life_tpu.obs.slo.SloTracker`
(access log, per-tenant RED metrics with trace-id exemplars, burn-rate
windows, all served live at ``/slo``).

Error mapping — admission control answers, it never wedges: a capacity
refusal (session cap, cell budget, full step queue, shutdown drain,
over-bound steps on a non-linear rule) is
**429** with the machine-readable ``reason`` (the same string on
``gol_serve_rejects_total{reason}``) and a ``Retry-After`` hint in the
body; a step that timed out is **503** (the body says whether it was
cancelled in-queue — board provably not advanced, retry safe); malformed
requests are 400; unknown ids 404; everything else 500 with the error
repr.  429/503 bodies carry the request's ``trace_id`` so a refused
client can hand support a clickable trace.  Board cells travel as base64
of the raw row-major
uint8 bytes (``board_b64`` + the height/width already in the doc) — JSON-
safe at any state alphabet without a 4-byte-per-cell integer list.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from typing import Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.obs import slo as slo_mod
from akka_game_of_life_tpu.obs.httpd import (
    JSON_TYPE,
    json_response,
    strip_query,
)
from akka_game_of_life_tpu.obs.tracing import TRACE_KEY
from akka_game_of_life_tpu.serve.federation import FederationRedirect
from akka_game_of_life_tpu.serve.sessions import AdmissionError, SessionRouter


def _doc(snapshot: dict, *, with_board: bool = True) -> dict:
    doc = dict(snapshot)
    board = doc.pop("board", None)
    if with_board and board is not None:
        doc["board_b64"] = base64.b64encode(
            np.ascontiguousarray(board).tobytes()
        ).decode("ascii")
    return doc


def decode_board_b64(doc: dict) -> np.ndarray:
    """Client-side twin of the ``board_b64`` encoding (bench/tests)."""
    raw = base64.b64decode(doc["board_b64"])
    return np.frombuffer(raw, dtype=np.uint8).reshape(
        doc["height"], doc["width"]
    )


# Create-side tenant relay: _create knows the tenant from the body; the
# request wrapper cuts the SLO line after dispatch on the same thread.
_tl = threading.local()


class BoardsRoute:
    """The ``/boards`` route handler (callable with the httpd route
    contract: ``(method, path, body) -> (status, ctype, bytes)``)."""

    def __init__(
        self,
        router: SessionRouter,
        *,
        tracer=None,
        slo=None,
        trace: Optional[bool] = None,
    ) -> None:
        self.router = router
        self.tracer = tracer if tracer is not None else getattr(
            router, "tracer", None
        )
        self.slo = slo
        if trace is None:
            trace = bool(
                getattr(
                    getattr(router, "config", None), "serve_trace", True
                )
            )
        self.trace = trace

    def __call__(self, method: str, path: str, body: bytes):
        # The server hands over the RAW path (query included); this route
        # dispatches on path segments, so normalize once at the door.
        path = strip_query(path)
        if not self.trace or self.tracer is None:
            return self._respond(method, path, body, None)
        with self.tracer.start(
            "serve.request",
            parent=self._adopt(body),
            method=method,
            path=path,
        ) as span:
            return self._respond(method, path, body, span)

    @staticmethod
    def _adopt(body: bytes):
        """Trace ctx a client rode in under the ``"_trace"`` body key
        (the route contract carries no headers); None mints a new root.
        The substring probe keeps the no-ctx hot path parse-free."""
        if not body or b'"_trace"' not in body:
            return None
        try:
            doc = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        ctx = doc.get(TRACE_KEY) if isinstance(doc, dict) else None
        return ctx if isinstance(ctx, dict) else None

    @staticmethod
    def _route_of(method: str, path: str) -> Tuple[Optional[str], str]:
        """(sid, route label) without raising — the SLO/span attribution
        must survive any path the dispatcher will 404."""
        parts = [p for p in path.split("/") if p]
        if parts[:1] != ["boards"]:
            return None, "other"
        sid = parts[1] if len(parts) > 1 else None
        if len(parts) >= 3:
            ok = len(parts) == 3 and parts[2] == "step" and method == "POST"
            return sid, "step" if ok else "other"
        if sid is None:
            return None, "create" if method == "POST" else "list"
        return sid, {"GET": "get", "DELETE": "delete"}.get(method, "other")

    def _respond(self, method: str, path: str, body: bytes, span):
        t0 = time.perf_counter()
        slo_mod.take_queue_wait()  # clear any stale relay from this thread
        _tl.tenant = None
        sid, route = self._route_of(method, path)
        reason: Optional[str] = None
        try:
            resp = self._dispatch(method, path, body)
        except FederationRedirect as e:
            # Federation: the board lives on a peer frontend and its
            # payload is too fat to proxy — 307 preserves the method and
            # points the client straight at the owner.
            resp = (
                307, JSON_TYPE,
                (json.dumps({"location": e.url}) + "\n").encode("utf-8"),
                {"Location": e.url},
            )
        except AdmissionError as e:
            reason = e.reason
            doc = {
                "error": str(e), "reason": e.reason, "retry_after_s": 0.1,
            }
            if isinstance(e.trace_link, dict):
                # The span that CAUSED the refusal (a failover 429's
                # serve.promote) — the click-through from the 429'd
                # request's trace into the promotion.
                doc["trace_link"] = dict(e.trace_link)
            if span is not None:
                doc["trace_id"] = span.trace_id
                if isinstance(e.trace_link, dict):
                    span.set(
                        link_trace_id=e.trace_link.get("trace_id"),
                        link_span_id=e.trace_link.get("span_id"),
                    )
            resp = json_response(429, doc)
        except KeyError as e:
            resp = json_response(404, {"error": f"no board {e.args[0]!r}"})
        except (ValueError, TypeError) as e:
            resp = json_response(400, {"error": str(e)})
        except TimeoutError as e:
            # The router's distinguished outcomes ("cancelled; board not
            # advanced" = a safe retry) ride str(e) — a generic 500 would
            # read as a route bug and lose the retry signal.
            doc = {"error": str(e), "retry_after_s": 1.0}
            if span is not None:
                doc["trace_id"] = span.trace_id
            resp = json_response(503, doc)
        status = resp[0]
        latency_s = time.perf_counter() - t0
        queue_wait_s = slo_mod.take_queue_wait()
        tenant = getattr(_tl, "tenant", None)
        if tenant is None and sid is not None:
            lookup = getattr(self.router, "tenant_of", None)
            tenant = lookup(sid) if lookup is not None else None
        tenant = tenant or "default"
        if span is not None:
            span.set(
                route=route, status=status, tenant=tenant,
                outcome=slo_mod.SloTracker.outcome_of(status),
            )
            if sid is not None:
                span.set(sid=sid)
            if reason is not None:
                span.set(reason=reason)
            if queue_wait_s is not None:
                span.set(queue_wait_s=round(queue_wait_s, 6))
        if self.slo is not None:
            self.slo.record(
                route=route,
                tenant=tenant,
                sid=sid,
                status=status,
                reason=reason,
                latency_s=latency_s,
                queue_wait_s=queue_wait_s,
                trace_id=span.trace_id if span is not None else None,
            )
        return resp

    def _dispatch(self, method: str, path: str, body: bytes):
        sid, action = self._parse_path(path)
        if sid is None:
            if method == "POST":
                return self._create(body)
            if method == "GET":
                return json_response(200, {"boards": self.router.list()})
            return json_response(405, {"error": f"{method} /boards"})
        if action == "step":
            if method != "POST":
                return json_response(405, {"error": f"{method} {path}"})
            return self._step(sid, body)
        if action is not None:
            raise KeyError(action)
        if method == "GET":
            return json_response(200, _doc(self.router.get(sid)))
        if method == "DELETE":
            self.router.delete(sid)
            return json_response(200, {"deleted": sid})
        return json_response(405, {"error": f"{method} {path}"})

    @staticmethod
    def _parse_path(path: str) -> Tuple[Optional[str], Optional[str]]:
        """"/boards" → (None, None); "/boards/<id>" → (id, None);
        "/boards/<id>/step" → (id, "step")."""
        parts = [p for p in path.split("/") if p]
        if parts[:1] != ["boards"] or len(parts) > 3:
            raise KeyError(path)
        sid = parts[1] if len(parts) > 1 else None
        action = parts[2] if len(parts) > 2 else None
        return sid, action

    @staticmethod
    def _payload(body: bytes) -> dict:
        if not body:
            return {}
        doc = json.loads(body.decode("utf-8"))
        if not isinstance(doc, dict):
            raise ValueError("request body must be a JSON object")
        doc.pop(TRACE_KEY, None)  # propagation envelope, not a field
        return doc

    def _create(self, body: bytes):
        doc = self._payload(body)
        allowed = {
            "tenant", "rule", "height", "width", "seed", "density", "sid",
        }
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"unknown fields: {sorted(unknown)}")
        tenant = str(doc.get("tenant", "default"))
        _tl.tenant = tenant
        kwargs = {}
        if doc.get("sid") is not None:
            # Client-chosen session id (the canary prober aims the crc32
            # shard hash with it); routers validate/refuse collisions.
            kwargs["sid"] = str(doc["sid"])
        snap = self.router.create(
            tenant=tenant,
            rule=doc.get("rule", "conway"),
            height=int(doc.get("height", 64)),
            width=int(doc.get("width", 64)),
            seed=int(doc.get("seed", 0)),
            density=float(doc.get("density", 0.5)),
            # The 201 deliberately carries no cells; skip the O(h·w) copy.
            with_board=False,
            **kwargs,
        )
        return json_response(201, _doc(snap, with_board=False))

    def _step(self, sid: str, body: bytes):
        doc = self._payload(body)
        steps = int(doc.get("steps", 1))
        epoch, digest = self.router.step(sid, steps)
        from akka_game_of_life_tpu.ops.digest import format_digest

        return json_response(
            200,
            {"id": sid, "epoch": epoch, "steps": steps,
             "digest": format_digest(digest)},
        )


class SloRoute:
    """``GET /slo`` → the live :meth:`SloTracker.summary` document."""

    def __init__(self, slo) -> None:
        self.slo = slo

    def __call__(self, method: str, path: str, body: bytes):
        if method != "GET":
            return json_response(405, {"error": f"{method} /slo"})
        return json_response(200, self.slo.summary())


def board_routes(
    router: SessionRouter, *, tracer=None, slo=None, trace=None
) -> dict:
    """The route table to mount on a MetricsServer (``routes=`` kwarg or
    ``add_route`` per entry): ``/boards`` plus ``/slo``.  ``slo=None``
    builds a default :class:`SloTracker` from the router's config and
    registry, so every serve surface is SLO-scored without wiring."""
    if slo is None:
        slo = slo_mod.SloTracker(
            getattr(router, "config", None),
            registry=getattr(router, "metrics", None),
            tracer=tracer if tracer is not None else getattr(
                router, "tracer", None
            ),
        )
    route = BoardsRoute(router, tracer=tracer, slo=slo, trace=trace)
    return {"/boards": route, "/slo": SloRoute(slo)}


def run_serve(config, *, registry=None, tracer=None) -> int:
    """The ``serve`` CLI role body: a SessionRouter + one obs endpoint
    carrying /metrics, /healthz, /trace, /slo, and /boards, until
    interrupted.  ``serve_canary`` adds the background digest-certified
    prober against the same (real) HTTP surface."""
    from akka_game_of_life_tpu.obs import MetricsServer, get_registry
    from akka_game_of_life_tpu.obs.events import NULL_EVENTS, EventLog
    from akka_game_of_life_tpu.obs.tracing import get_tracer

    registry = registry if registry is not None else get_registry()
    tracer = tracer if tracer is not None else get_tracer()
    events = (
        EventLog(config.log_events, node="serve", recorder=tracer.flight)
        if getattr(config, "log_events", None)
        else NULL_EVENTS
    )
    router = SessionRouter(
        config, registry=registry, tracer=tracer, events=events
    )
    slo = slo_mod.SloTracker(
        config, registry=registry, tracer=tracer, events=events,
    )
    # Compile & cost observatory: the serve role is the storm detector's
    # prime customer — a novel (class, length) program compiling after
    # warmup is a latency cliff for live tenants.  Storm alerts fire into
    # this role's event log + flight recorder; /profile captures land
    # beside the flight dumps.
    from akka_game_of_life_tpu.obs.programs import get_programs, http_routes
    from akka_game_of_life_tpu.runtime.profiling import ProfilerCapture

    programs = get_programs().configure(
        node="serve",
        events=events,
        flight=tracer.flight,
        metrics=registry,
        enabled=config.obs_programs,
    )
    profiler = ProfilerCapture(
        config.flight_dir or "artifacts",
        node="serve",
        max_seconds=config.obs_profile_max_s,
        min_interval_s=config.obs_profile_min_interval_s,
    )

    def health() -> dict:
        doc = {"ok": True, "role": "serve", **router.stats()}
        doc["programs"] = programs.health_summary()
        return doc

    routes = dict(http_routes(registry=programs, profile=profiler.capture))
    routes.update(board_routes(router, tracer=tracer, slo=slo))
    server = MetricsServer(
        registry,
        port=config.metrics_port,
        health=health,
        tracer=tracer,
        routes=routes,
    )
    canary = None
    if config.serve_canary:
        from akka_game_of_life_tpu.serve.canary import CanaryProber

        canary = CanaryProber(
            config,
            base=f"http://127.0.0.1:{server.port}",
            registry=registry,
            tracer=tracer,
            events=events,
        )
        canary.start()
    print(
        f"serving /boards (+/metrics,/healthz,/trace,/slo,"
        f"/programs,/cost,/profile) on "
        f":{server.port} — "
        f"max {router.max_sessions} sessions, {router.max_cells} cells, "
        f"size classes {list(router.size_classes)}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        # A real drain, not just the word: refuse NEW work (429 reason
        # "draining") and run the admitted queue dry before closing — an
        # accepted job is never failed with "router closed" because the
        # operator sent SIGTERM.
        print("serve: interrupted; draining", flush=True)
        if canary is not None:
            canary.close()
        drained = router.drain()
        print(
            "serve: drained" if drained
            else "serve: drain timed out; aborting pending jobs",
            flush=True,
        )
        return 130
    finally:
        if canary is not None:
            canary.close()
        server.close()
        slo.close()
        if events is not NULL_EVENTS:
            events.close()
        router.close()
