"""Batched multi-tenant stepping: many small boards in one device program.

Everywhere else in the codebase the rule is a *compile-time constant* the
kernel closes over (``ops/stencil.py``: XLA constant-folds the two bitmask
ints into the stencil fusion).  That is the right trade for one huge board
— and exactly the wrong one for serving millions of users, where thousands
of small boards with *heterogeneous* rules must advance together: one
compiled program per (rule, shape, steps) would thrash the compile cache
and serialize the device.

This module flips the trade, the CAX/CAT shape (PAPERS.md): ``vmap`` the
step over a batched ``[B, C, C]`` leading dimension and lift the rule
masks from closure constants to **traced per-board operands** —
``(birth_mask, survive_mask, states)`` uint32/int32 arrays ride the batch
like the boards do, so one compiled program serves every outer-totalistic
rule (binary life-likes AND multi-state Generations decay) at once.

Mixed shapes bucket into a few padded **size classes**: a board of logical
shape ``(h, w)`` occupies the top-left corner of a ``C×C`` slot (zeros
beyond it) and steps toroidally *on its own h×w region* via modular index
gathers — ``(r+dy) mod h`` never reads padding, and the output mask keeps
padding dead — so the batched step is bit-identical to the single-board
toroidal step at every shape ≤ the class side.  Per-board step counts are
a traced operand too (a scan-step applies only while ``i < n[b]``), with
the scan length and batch size rounded up to powers of two so the whole
traffic mix compiles into O(classes · log(steps) · log(B)) programs.

The per-board digest lanes (``ops.digest.digest_dense_batch``) come back
from the SAME jitted call: certification rides the step program, ~8 bytes
per board.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from akka_game_of_life_tpu.ops import digest as odigest
from akka_game_of_life_tpu.ops.rules import Rule
from akka_game_of_life_tpu.runtime.config import parse_size_classes

__all__ = [
    "DEFAULT_SIZE_CLASSES",
    "batch_step_fn",
    "memo_block_step_fn",
    "next_pow2",
    "parse_size_classes",  # canonical home: runtime.config (validation)
    "rule_operands",
    "size_class",
]

STATE_DTYPE = jnp.uint8
_I = jnp.int32
_U = jnp.uint32

# Default padded size classes (square sides).  Small powers of two: the
# serving plane targets many small per-user boards, not the 65536² headline
# board — that one stays on the single-board kernels.
DEFAULT_SIZE_CLASSES: Tuple[int, ...] = (32, 64, 128, 256)


def size_class(
    height: int, width: int, classes: Sequence[int] = DEFAULT_SIZE_CLASSES
) -> Optional[int]:
    """The smallest class side that fits an (height, width) board, or None
    when the board exceeds every class (the caller's 400, not a crash)."""
    side = max(height, width)
    for c in classes:
        if side <= c:
            return c
    return None


# The canonical quantizer lives with the other gating math in ops/sparse
# (ops must not import serve); re-exported here because it is part of this
# module's public surface (__all__) and the sessions/tests call it as
# sbatch.next_pow2.
from akka_game_of_life_tpu.ops.sparse import next_pow2  # noqa: E402,F401


def rule_operands(rule: Rule) -> Tuple[int, int, int]:
    """A rule as traced-operand data: (birth_mask, survive_mask, states).
    Only outer-totalistic families serve batched — wireworld's transition
    is not mask-encodable and LtL needs radius-R geometry."""
    if rule.kind != "totalistic":
        raise ValueError(
            f"the serving plane steps outer-totalistic rules only "
            f"(life-like and Generations); {rule} is kind={rule.kind!r}"
        )
    return rule.birth_mask, rule.survive_mask, rule.states


def _mod_idx(n_static: int, d: int, m) -> jax.Array:
    """Index vector ``(i + d) mod m`` over a static range — the toroidal
    shift on a traced live extent ``m`` ≤ ``n_static`` (jnp ``%`` is
    Python-signed: -1 % m == m-1; indices land in [0, m), so gathers
    through these never read padding)."""
    return (jnp.arange(n_static, dtype=_I) + d) % m


def _neighbor_counts(alive: jax.Array, h, w) -> jax.Array:
    """Moore-8 live-neighbor counts, toroidal on the [:h, :w] live region
    of a padded slot.  Separable: three column gathers then three row
    gathers (6 gathers per step), minus the center.  Rows/cols ≥ the live
    extent compute garbage that the caller's region mask discards."""
    ch, cw = alive.shape
    s1 = jnp.zeros_like(alive)
    for d in (-1, 0, 1):
        s1 = s1 + jnp.take(alive, _mod_idx(cw, d, w), axis=1)
    acc = jnp.zeros_like(alive)
    for d in (-1, 0, 1):
        acc = acc + jnp.take(s1, _mod_idx(ch, d, h), axis=0)
    return acc - alive


def _step_once(board, birth_mask, survive_mask, states, h, w):
    """One toroidal step of ONE padded board slot; the rule is four traced
    scalars.  Bit-identical to ``ops.stencil.step`` of the ``[:h, :w]``
    region for every outer-totalistic rule, including Generations decay
    (live cell failing survival enters state 2 and decays to 0; refractory
    cells block birth and never count as neighbors)."""
    ch, cw = board.shape
    counts = _neighbor_counts((board == 1).astype(STATE_DTYPE), h, w)
    c = counts.astype(_U)
    birth = ((jnp.asarray(birth_mask, _U) >> c) & _U(1)).astype(STATE_DTYPE)
    survive = ((jnp.asarray(survive_mask, _U) >> c) & _U(1)).astype(STATE_DTYPE)
    one = jnp.asarray(1, STATE_DTYPE)
    two = jnp.asarray(2, STATE_DTYPE)
    zero = jnp.asarray(0, STATE_DTYPE)
    states = jnp.asarray(states, _I)
    # Binary rules (states == 2) fall out of the Generations form: the
    # first refractory state only exists when states > 2, and the decay
    # branch never sees a state ≥ 2 cell.
    live_next = jnp.where(
        survive == 1, one, jnp.where(states > 2, two, zero)
    )
    bumped = board.astype(_I) + 1
    decayed = jnp.where(bumped < states, bumped, 0).astype(STATE_DTYPE)
    out = jnp.where(
        board == 0, birth, jnp.where(board == 1, live_next, decayed)
    )
    # Padding stays dead: birth in the garbage region (or from a B0-style
    # mask) must not leak live cells outside [:h, :w].
    rows = jnp.arange(ch, dtype=_I)[:, None]
    cols = jnp.arange(cw, dtype=_I)[None, :]
    return jnp.where((rows < h) & (cols < w), out, zero)


@functools.lru_cache(maxsize=None)
def batch_step_fn(class_side: int, length: int):
    """The jitted batched advance for one size class (cached per
    ``(class_side, length)``; the caller also quantizes the batch dim to
    powers of two, so the program count stays O(classes · log steps ·
    log B) whatever the traffic mix).

    Signature of the returned callable::

        boards' [B,C,C]u8, lanes [B,2]u32 = run(
            boards [B,C,C]u8,   # zero-padded beyond each [:h,:w] region
            birth   [B]u32,     # per-board Rule.birth_mask
            survive [B]u32,     # per-board Rule.survive_mask
            states  [B]i32,     # per-board state count (2 = binary)
            h, w    [B]i32,     # per-board live extents (1..C)
            n       [B]i32,     # per-board step counts (0..length)
        )

    Board b advances exactly ``n[b]`` toroidal epochs (scan iterations
    past its count are identity), then its digest lanes are folded in the
    same program — certification ships with the step."""

    def one(board, birth, survive, states, h, w, n):
        def body(s, i):
            stepped = _step_once(s, birth, survive, states, h, w)
            return jnp.where(i < n, stepped, s), None

        out, _ = jax.lax.scan(body, board, jnp.arange(length, dtype=_I))
        return out

    @jax.jit
    def run(boards, birth, survive, states, h, w, n):
        stepped = jax.vmap(one)(boards, birth, survive, states, h, w, n)
        lanes = odigest.digest_dense_batch(stepped, w)
        return stepped, lanes

    from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost

    return registered_jit(
        "serve_batch", (class_side, length), run,
        # Every board in the batch scans `length` iterations (identity past
        # its own n) — the padded cost is what the device actually runs.
        cost=lambda boards, *rest: stencil_cost(
            class_side, class_side, length, boards=boards.shape[0]
        ),
    )


@functools.lru_cache(maxsize=None)
def memo_block_step_fn(block: int):
    """The macro-cell miss program (``serve/memo.py``): advance a batch of
    B-sided context blocks exactly S = B/4 toroidal epochs and return their
    T-sided centers (T = B/2) — the payload a memo cache entry stores.

    One program per block size, for EVERY rule and EVERY session: the rule
    masks ride as traced per-block operands exactly like
    :func:`batch_step_fn`, and blocks are always full B×B (no live-extent
    masks — the codec only emits exact blocks), so the whole memo plane
    compiles O(1) programs.  The caller pads the batch dim to a power of
    two (zero blocks under a zero rule are inert).

    Signature of the returned callable::

        centers [N,T,T]u8 = run(
            blocks  [N,B,B]u8,  # toroidal context blocks
            birth   [N]u32,     # per-block Rule.birth_mask
            survive [N]u32,     # per-block Rule.survive_mask
            states  [N]i32,     # per-block state count (2 = binary)
        )

    Correctness of the toroidal shortcut is argued in
    ``ops/macroblock.py``: wrap corruption travels inward one cell per
    step and never reaches the center within S steps."""
    tile = block // 2
    steps = block // 4
    h = jnp.asarray(block, _I)
    w = jnp.asarray(block, _I)

    def one(blk, birth, survive, states):
        def body(s, _):
            return _step_once(s, birth, survive, states, h, w), None

        out, _ = jax.lax.scan(body, blk, None, length=steps)
        return jax.lax.dynamic_slice(
            out, (steps, steps), (tile, tile)
        )

    @jax.jit
    def run(blocks, birth, survive, states):
        return jax.vmap(one)(blocks, birth, survive, states)

    from akka_game_of_life_tpu.obs.programs import registered_jit, stencil_cost

    return registered_jit(
        "serve_memo", (block, steps), run,
        cost=lambda blocks, *rest: stencil_cost(
            block, block, steps, boards=blocks.shape[0]
        ),
    )
