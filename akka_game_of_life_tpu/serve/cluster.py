"""Cluster-sharded serving: the session router as the frontend's surface.

PR 7's serving plane batches small boards on ONE process; PR 6's elastic
cluster runs ONE big board across workers.  This module fuses them: the
:class:`ClusterServePlane` is the cluster frontend's tenant-facing router —
it owns cluster-wide admission and the session *index*, while the boards
themselves live sharded across workers, each worker running its own
vmapped batch engine (:mod:`serve.worker` wraps PR 7's ``SessionRouter``
unchanged as the per-worker core).  Serve capacity then scales with
``--grow-to``: boards/sec multiplies by worker count because every worker
ticks its own device program concurrently.

**Shard routing.**  Session ids hash onto ``serve_shards`` virtual shards
(crc32, stable across processes); each shard is owned by one worker.  A
session's whole life stays on its shard's owner — the board is resident
worker-side between ticks (the Casper access-pattern lesson: move the
session once, not its cells every tick), and ops for one worker coalesce
into single ``SERVE_OPS`` frames (the PR 4 coalescing discipline on the
control plane).

**Shard migration.**  The PR 6 Rebalancer learns session shards as a
second resource type (:meth:`runtime.rebalance.Rebalancer.plan_shards`):
load-driven spreading (a late joiner starts receiving shards) and
drain-driven evacuation ride the same freeze → transfer → certify →
commit protocol as tile migration, at session granularity — every
exported board is certified via ``digest_payload_np`` before commit, ops
arriving mid-move are *held* and replayed at the new owner, and a
mid-traffic SIGTERM drain loses zero admitted jobs.  A shard with no
sessions flips ownership without any wire protocol.

**Session replication & crash failover.**  Each owned shard gets a
*replica* worker (sticky once assigned — churn discards acked standby
state; rendezvous-hashed by (shard, worker) on fresh assignment so a
membership change re-homes only the shards that must move; never the
primary): the primary streams dirty session snapshots —
bit-packed boards plus digest lanes — to the frontend
(``SHARD_REPLICATE``), which relays them to the replica as ``replicate``
ops on the replica's op FIFO and acks the primary with the per-session
epoch watermark (``SHARD_REPLICATE_ACK``, on the primary's op FIFO — so
replication can never reorder against shard control).  On worker loss
the frontend *promotes* replicas instead of 404ing: the replica
certifies every standby payload against its streamed lanes and installs
it, promoted sessions resume from their last acked replicated epoch, and
ops caught in the window answer the retryable 429 ``failover`` (the
board is provably at its replicated epoch) rather than 404.  When no
second placeable worker exists the plane degrades honestly to
single-copy mode (``gol_serve_single_copy_shards`` + the /healthz flag;
primaries park their streams), and replication lag past
``serve_replicate_max_lag_s`` is surfaced loudly, never silently
unbounded.  A shard and its replica do not co-reside — the rebalancer
avoids a shard's replica as a migration destination (falling back only
when it is the last placeable member, with the replica re-homed in the
same lock hold that commits the move), and drains re-home replicas (a
draining worker is released only once nothing replicates to it).

**Tiled (mega-board) sessions.**  A board above the largest size class is
no longer rejected: it is admitted as a first-class *tiled* session.
With ``serve_tiled_resident`` on (the default) the session is
WORKER-RESIDENT: chunks install once on their assigned workers and stay
device-side across steps; per step request the frontend sends ONE op per
worker naming the barrier epoch, the per-round step counts, and the
chunk→owner aiming map — the workers then chain the rounds themselves,
exchanging O(perimeter) halo strips worker-to-worker (``TILED_HALO``
over the peer plane, batched and bit-packed per destination) and
batching each round's ready chunks into one vmapped device call.  The
frontend re-enters only at the request barrier (merged digest lanes, 16
bytes a chunk) and on renders (``GET ?with_board=1`` pays the one
remaining O(area) fetch).  Chunks snapshot at a barrier cadence and
stream to per-chunk replicas through the PR 14 watermark machinery; a
worker loss PROMOTES at the session's certified epoch (lost chunks from
replica standby, survivors rolled back to their local snapshot — the
whole session resumes consistent, windowed ops answer 429 ``failover``),
and the Rebalancer re-homes resident chunks digest-certified under the
session's steplock (a move can never interleave with an epoch barrier).
With the gate off, the PR 13 ship-per-round path runs: the frontend
keeps the board and each step fans ``step_raw`` chunks with full state
across ALL workers (pure operands; a crash mid-chunk replays
elsewhere).  Either way per-tile digest lanes computed at global offsets
merge into the session digest — the same certification plane as the
big-board cluster.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.obs import get_registry
from akka_game_of_life_tpu.obs import slo as _slo
from akka_game_of_life_tpu.obs.tracing import TRACE_KEY, current, get_tracer
from akka_game_of_life_tpu.ops import digest as odigest
from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.rebalance import Rebalancer
from akka_game_of_life_tpu.runtime.wire import pack_tile, unpack_tile
from akka_game_of_life_tpu.serve import batch as sbatch
from akka_game_of_life_tpu.serve.sessions import (
    JOB_GRACE_S,
    JOB_TIMEOUT_S,
    AdmissionError,
    rendezvous_pick,
    shard_of,
    validate_create,
)
from akka_game_of_life_tpu.utils.patterns import random_grid

# Bounded re-routes for one op (shard moved under it, worker lost before
# the frame went out, worker answered "migrating"): each retry lands on a
# live owner or fails loudly — never a silent drop, never a spin.
OP_MAX_RETRIES = 4
# Tile-chunk ops of a mega-board step are pure functions of their
# operands: a worker loss mid-chunk replays the SAME chunk elsewhere.
TILE_OP_RETRIES = 3


class _Entry:
    """Cluster-side session index row: where a session lives and the last
    observed (epoch, digest) — the authoritative board stays worker-side
    (or plane-side for tiled sessions)."""

    __slots__ = (
        "sid", "tenant", "kind", "rule_s", "height", "width",
        "seed", "density", "shard", "epoch", "digest", "last_used",
        "evicting", "repl_epoch", "repl_dirty_since",
    )

    def __init__(self, sid, tenant, kind, rule_s, height, width, seed,
                 density, shard):
        self.sid = sid
        self.tenant = tenant
        self.kind = kind  # "batch" | "tiled"
        self.rule_s = rule_s
        self.height = height
        self.width = width
        self.seed = seed
        self.density = density
        self.shard = shard  # None for tiled sessions (plane-resident)
        self.epoch = 0
        self.digest: Optional[str] = None
        # TTL bookkeeping: the FRONTEND owns idle eviction in cluster mode
        # (workers get serve_ttl_s=0 — a local eviction would silently
        # leak the cluster admission budget this index charges).
        self.last_used = time.monotonic()
        self.evicting = False
        # Replication watermark: the highest epoch the shard's replica has
        # ACKED for this session (-1 = nothing replicated — a promotion
        # cannot save it), and when the session first advanced past it
        # (None = clean; the lag gauge reads this).
        self.repl_epoch = -1
        self.repl_dirty_since: Optional[float] = time.monotonic()

    def mark_dirty(self, now: float) -> None:
        """Epoch moved past the acked watermark: start the lag clock
        (idempotent — the clock keeps its ORIGINAL dirty time until the
        replica catches all the way up)."""
        if (
            self.shard is not None
            and self.epoch > self.repl_epoch
            and self.repl_dirty_since is None
        ):
            self.repl_dirty_since = now

    def summary(self, owner: Optional[str]) -> dict:
        return {
            "id": self.sid,
            "tenant": self.tenant,
            "rule": self.rule_s,
            "kind": self.kind,
            "height": self.height,
            "width": self.width,
            "seed": self.seed,
            "epoch": self.epoch,
            "digest": self.digest,
            "shard": self.shard,
            "worker": owner,
        }


class _TiledSession:
    """A frontend-resident mega-board and its tile grid (the
    ship-per-round mode: ``serve_tiled_resident`` off)."""

    mode = "ship"

    __slots__ = ("board", "lanes", "epoch", "tiles", "steplock")

    def __init__(self, board: np.ndarray, tile_side: int) -> None:
        self.board = board
        self.lanes = odigest.digest_dense_np(board)
        self.epoch = 0
        h, w = board.shape
        self.tiles: List[Tuple[int, int, int, int]] = [
            (gy, gx, min(tile_side, h - gy), min(tile_side, w - gx))
            for gy in range(0, h, tile_side)
            for gx in range(0, w, tile_side)
        ]
        # Serializes concurrent steps of ONE mega session (each step is a
        # multi-chunk read-modify-write of the resident board); different
        # sessions step fully in parallel.
        self.steplock = threading.Lock()


class _ResidentTiled:
    """A worker-resident mega-board session: the frontend holds only the
    chunk grid, placement maps, and digest/watermark bookkeeping — the
    cells live on the workers and per-round traffic is O(perimeter) peer
    halo strips, never board state through here."""

    mode = "resident"

    __slots__ = (
        "sid", "rule_s", "H", "W", "k", "ny", "nx", "tiles",
        "owner", "replica", "acked", "epoch", "lanes", "population",
        "steplock", "promoting", "round_idx", "parked",
    )

    def __init__(self, sid: str, rule_s: str, board: np.ndarray,
                 tile_side: int, tile_chunk: int) -> None:
        self.sid = sid
        self.rule_s = rule_s
        self.H, self.W = board.shape
        grid_y = range(0, self.H, tile_side)
        grid_x = range(0, self.W, tile_side)
        self.ny = len(grid_y)
        self.nx = len(grid_x)
        # (cy, cx) -> (gy, gx, th, tw)
        self.tiles: Dict[Tuple[int, int], Tuple[int, int, int, int]] = {
            (cy, cx): (gy, gx,
                       min(tile_side, self.H - gy),
                       min(tile_side, self.W - gx))
            for cy, gy in enumerate(grid_y)
            for cx, gx in enumerate(grid_x)
        }
        # The halo width must fit inside every neighbor chunk (a strip is
        # cut from ONE chunk's interior), so ragged edge tiles clamp the
        # per-round epoch count.
        self.k = max(1, min(
            tile_chunk,
            min(t[2] for t in self.tiles.values()),
            min(t[3] for t in self.tiles.values()),
        ))
        self.owner: Dict[Tuple[int, int], str] = {}
        self.replica: Dict[Tuple[int, int], Optional[str]] = {
            c: None for c in self.tiles
        }
        # Per-chunk replication watermark: newest snapshot epoch the
        # chunk's replica has acked (-1 = nothing; the session's
        # certified resume point is the min over chunks).
        self.acked: Dict[Tuple[int, int], int] = {
            c: -1 for c in self.tiles
        }
        self.epoch = 0
        self.lanes = odigest.digest_dense_np(board)
        self.population = int((board == 1).sum())
        self.steplock = threading.Lock()
        self.promoting = False
        self.round_idx = 0
        self.parked = False

    def certified(self, chunks=None) -> int:
        """The epoch the session can provably resume at after losing
        ``chunks`` (default: any chunk): every lost chunk's replica must
        hold an acked snapshot there, and survivors' local history is
        floor-pruned no deeper (so they hold it too)."""
        keys = self.tiles if chunks is None else chunks
        return min((self.acked[c] for c in keys), default=-1)

    def meta(self) -> dict:
        return {
            "rule": self.rule_s, "H": self.H, "W": self.W,
            "grid": [self.ny, self.nx], "k": self.k,
        }


class _Pending:
    """One forwarded op awaiting its SERVE_RESULT (or internal callback)."""

    __slots__ = (
        "rid", "op", "sid", "shard", "kind", "member", "sent",
        "retries", "event", "result", "error", "on_done",
    )

    def __init__(self, rid, op, *, sid=None, shard=None, kind="",
                 member=None, on_done=None):
        self.rid = rid
        self.op = op
        self.sid = sid
        self.shard = shard
        self.kind = kind
        self.member = member  # None until routed; direct ops pre-target
        self.sent = False
        self.retries = 0
        self.event = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.on_done = on_done


class ClusterServePlane:
    """The frontend's tenant-facing serve surface (SessionRouter-shaped:
    ``BoardsRoute`` mounts it unchanged).  Thread layout: HTTP/caller
    threads block on per-op events; one flusher thread coalesces queued
    ops into per-worker SERVE_OPS frames; the frontend's reader threads
    deliver results via :meth:`on_result`/:meth:`on_shard_state`; the
    maintenance loop drives :meth:`poll`.

    Lock discipline: ``self._lock`` (RLock) orders the shard table, the
    session index, and the op queues.  NOTHING is sent on the wire while
    it is held — sends go through the frontend's ``_safe_send``, whose
    failure path takes the frontend lock (frontend lock → plane lock is
    the only permitted nesting order)."""

    def __init__(
        self,
        config,
        membership,
        send,
        *,
        registry=None,
        tracer=None,
        events=None,
    ) -> None:
        self.config = config
        self.membership = membership
        self._send_to = send  # callable(Member, dict); never under _lock
        self.n_shards = int(config.serve_shards)
        self.max_sessions = config.serve_max_sessions
        self.max_cells = config.serve_max_cells
        self.max_steps = config.serve_max_steps
        self.size_classes = sbatch.parse_size_classes(
            config.serve_size_classes
        )
        self.tile_side = self.size_classes[-1]
        self.tile_chunk = max(1, int(config.serve_tile_chunk))
        self.metrics = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.events = events
        self._m_rejects = self.metrics.counter(
            "gol_serve_rejects_total", labelnames=("reason",)
        )
        self._m_shards = self.metrics.gauge(
            "gol_serve_shards",
            "Session shards owned, per serve worker",
            ("member",),
        )
        self._m_shard_sessions = self.metrics.gauge(
            "gol_serve_shard_sessions",
            "Sessions resident, per serve worker",
            ("member",),
        )
        self._m_wqueue = self.metrics.gauge(
            "gol_serve_worker_queue_depth",
            "Serve ops queued toward each worker (unsent + unanswered)",
            ("member",),
        )
        self._m_ops = self.metrics.counter("gol_serve_ops_total")
        self._m_frames = self.metrics.counter("gol_serve_op_frames_total")
        self._m_migrations = self.metrics.counter(
            "gol_serve_shard_migrations_total"
        )
        self._m_migration_aborts = self.metrics.counter(
            "gol_serve_shard_migration_aborts_total"
        )
        self._m_tiled = self.metrics.gauge("gol_serve_tiled_sessions")
        self._m_tiled_bytes = self.metrics.histogram(
            "gol_serve_tiled_bytes_round",
            "Cell-state bytes moved per tiled step round",
            buckets=(2**10, 2**12, 2**14, 2**16, 2**18, 2**20, 2**22, 2**24),
        )
        self._m_chunk_migrations = self.metrics.counter(
            "gol_serve_tiled_chunk_migrations_total"
        )
        self._m_evictions = self.metrics.counter(
            "gol_serve_session_evictions_total"
        )
        self.ttl_s = config.serve_ttl_s
        self._m_digest_checks = self.metrics.counter("gol_digest_checks_total")
        self._m_digest_mismatches = self.metrics.counter(
            "gol_digest_mismatches_total"
        )
        # Session replication & failover (docs/OPERATIONS.md "Session
        # replication & failover").
        self._replicate = bool(config.serve_replicate)
        self.repl_max_lag_s = float(config.serve_replicate_max_lag_s)
        self._m_repl_lag = self.metrics.gauge(
            "gol_serve_replication_lag_seconds",
            "Age of the oldest un-acked session update, per shard",
            ("shard",),
        )
        self._m_repl_bytes = self.metrics.counter(
            "gol_serve_replica_bytes_total"
        )
        self._m_promotions = self.metrics.counter(
            "gol_serve_promotions_total"
        )
        self._m_single_copy = self.metrics.gauge(
            "gol_serve_single_copy_shards"
        )
        self._m_sessions_lost = self.metrics.counter(
            "gol_serve_sessions_lost_total"
        )

        # The elastic planner's second resource type rides a plane-owned
        # Rebalancer: same policy/backoff machinery, zero contention with
        # tile moves (budget and cooldowns are per-instance).
        self.rebalancer = Rebalancer(config)
        # ...and the THIRD resource type (resident tiled chunks) gets its
        # own instance too — chunk moves must not contend with shard
        # moves for the in-flight budget.
        self.tiled_rebalancer = Rebalancer(config)
        self.tiled_resident = bool(config.serve_tiled_resident)
        self.tiled_snap_rounds = int(config.serve_tiled_resident_snapshot)
        # Request-trace propagation gate (one bool read on the hot path;
        # the attach itself is a thread-local peek + dict store).
        self._trace = bool(getattr(config, "serve_trace", True))

        self._lock = threading.RLock()
        # Flusher wake signal: an Event, not the Condition — the routing
        # fast path sets it WITHOUT holding the plane lock.
        self._wake = threading.Event()
        self._ids = itertools.count(1)
        self._rids = itertools.count(1)
        self._rr = itertools.count()  # tiled-chunk round-robin cursor
        self.shard_owner: Dict[int, Optional[str]] = {  # graftlint: guarded-by _lock
            s: None for s in range(self.n_shards)
        }
        self.sessions: Dict[str, _Entry] = {}  # graftlint: guarded-by _lock
        self.tiled: Dict[str, _TiledSession] = {}  # graftlint: guarded-by _lock
        self._cells = 0  # graftlint: guarded-by _lock
        self._pending: Dict[int, _Pending] = {}  # graftlint: guarded-by _lock
        self._outq: Dict[str, deque] = {}  # graftlint: guarded-by _lock
        self._held: Dict[int, List[_Pending]] = {}  # graftlint: guarded-by _lock
        self.shard_replica: Dict[int, Optional[str]] = {}  # graftlint: guarded-by _lock
        self._promoting: Dict[int, dict] = {}  # graftlint: guarded-by _lock
        self._tiled_promoting: Dict[str, dict] = {}  # graftlint: guarded-by _lock
        # The routing fast path's versioned immutable lookup snapshot:
        # (owner dict, blocked frozenset), REPLACED (never mutated) under
        # the lock whenever the shard table, in-flight move set, or
        # promotion set changes — readers take the reference lock-free and
        # revalidate identity under one short lock hold before enqueueing.
        self._routes: Tuple[Dict[int, str], frozenset] = ({}, frozenset())
        self._lag_alert: set = set()  # graftlint: guarded-by _lock
        self._lag_minted: set = set()  # graftlint: guarded-by _lock
        self._lag_snapshot: Dict[int, float] = {}  # graftlint: guarded-by _lock
        self._draining = False  # graftlint: guarded-by _lock
        self._stopped = False  # graftlint: guarded-by _lock
        self._health_snapshot: Dict[str, dict] = {
            "shards": {}, "sessions": {}, "queue_depths": {},
        }
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True, name="serve-flusher"
        )
        self._flusher.start()

    # -- admission ------------------------------------------------------------

    def _reject(self, reason: str, detail: str, link=None) -> None:
        """Refuse one op.  ``link`` (a span or its ctx dict) ties the
        refusal to the event that CAUSED it — a failover 429 carries the
        ``serve.promote`` span's trace so the tenant's trace clicks
        through to the promotion that bounced it."""
        self._m_rejects.labels(reason=reason).inc()
        if link is not None and hasattr(link, "ctx"):
            link = link.ctx
        raise AdmissionError(reason, detail, trace_link=link)

    def _tiled_link_locked(self, sid: str):
        """The in-flight tiled promotion/resync span ctx for ``sid`` (or
        None) — the link a tiled failover 429 carries (caller holds the
        lock)."""
        info = self._tiled_promoting.get(sid)
        span = info.get("span") if info is not None else None
        return span.ctx if span is not None else None

    def _admit_locked(self, height: int, width: int) -> None:
        """Cluster-wide admission — the budget the frontend owns (worker
        caps are only the backstop behind it)."""
        if self._stopped:
            raise RuntimeError("router is closed")
        if self._draining:
            self._reject("draining", "cluster serve plane is draining")
        if not self.membership.alive_members():
            self._reject(
                "no_workers",
                "no serve workers joined yet; retry once the cluster has "
                "capacity",
            )
        if len(self.sessions) >= self.max_sessions:
            self._reject(
                "max_sessions",
                f"cluster session cap {self.max_sessions} reached",
            )
        if self._cells + height * width > self.max_cells:
            self._reject(
                "max_cells",
                f"cluster cell budget {self.max_cells} would be exceeded "
                f"({self._cells} in use, {height * width} asked)",
            )

    # -- session lifecycle (the SessionRouter-shaped surface) -----------------

    def create(
        self,
        tenant: str = "default",
        rule="conway",
        height: int = 64,
        width: int = 64,
        seed: int = 0,
        density: float = 0.5,
        with_board: bool = True,
        sid: Optional[str] = None,
    ) -> dict:
        tenant = str(tenant)
        rule_r = validate_create(tenant, rule, height, width, density)
        if sid is not None:
            # Caller-chosen id (the canary prober aims crc32 at a specific
            # shard with it): same contract as the worker router — refuse
            # collisions, never silently replace a tenant's board.
            sid = str(sid)
            if not sid or len(sid) > 128:
                raise ValueError(f"session id {sid!r} must be 1-128 chars")
        tiled = sbatch.size_class(height, width, self.size_classes) is None
        with self._lock:
            self._admit_locked(height, width)
            if sid is None:
                sid = f"s{next(self._ids):08x}"
            elif sid in self.sessions:
                raise ValueError(f"session id {sid!r} already exists")
            entry = _Entry(
                sid, tenant, "tiled" if tiled else "batch",
                rule_r.rulestring(), height, width, seed, density,
                None if tiled else shard_of(sid, self.n_shards),
            )
            # Charged against the budget NOW — a racing create must not
            # slip past the cap while this one's worker round-trip runs.
            self.sessions[sid] = entry
            self._cells += height * width
        if tiled:
            board = random_grid((height, width), density=density, seed=seed)
            if self.tiled_resident:
                try:
                    t = self._install_tiled(sid, entry, board)
                except BaseException:
                    with self._lock:
                        if self.sessions.get(sid) is entry:
                            del self.sessions[sid]
                            self._cells -= height * width
                    raise
            else:
                t = _TiledSession(board, self.tile_side)
            with self._lock:
                self.tiled[sid] = t
                entry.digest = odigest.format_digest(odigest.value(t.lanes))
                self._m_tiled.set(len(self.tiled))
            doc = self._tiled_doc(
                sid, entry, t, with_board=with_board, board=board
            )
            return doc
        op = {
            "op": "create", "rid": 0, "sid": sid, "tenant": tenant,
            "rule": rule_r.rulestring(), "height": height, "width": width,
            "seed": seed, "density": density,
        }
        p = None
        try:
            # Inside the try: a routing refusal (no_workers between the
            # admission check and here) must refund the entry/budget just
            # charged, not leak a ghost index row.
            p = self._submit(op, sid=sid, shard=entry.shard, kind="create")
            self._await(p)
        except BaseException as e:
            # A SENT create that timed out has an UNKNOWN outcome: the
            # worker may still apply it after we refund the budget here.
            # A compensating delete rides the same FIFO lane — it runs
            # after the create if that applied (404s harmlessly if not),
            # so the worker-local backstop can never leak orphan boards.
            cleanup = p is not None and p.sent and isinstance(e, TimeoutError)
            with self._lock:
                if self.sessions.get(sid) is entry:
                    del self.sessions[sid]
                    self._cells -= height * width
            if cleanup:
                try:
                    self._submit(
                        {"op": "delete", "rid": 0, "sid": sid},
                        sid=sid, shard=entry.shard, kind="evict",
                        on_done=lambda _p: None,
                    )
                except Exception:  # noqa: BLE001 — best-effort compensation
                    pass
            raise
        doc = dict(p.result["doc"])
        with self._lock:
            entry.epoch = int(doc.get("epoch", 0))
            entry.digest = doc.get("digest")
            entry.mark_dirty(time.monotonic())
        return doc

    def _tiled_doc(self, sid, entry, t, *, with_board: bool,
                   board=None) -> dict:
        doc = {
            "id": sid,
            "tenant": entry.tenant,
            "rule": entry.rule_s,
            "kind": "tiled",
            "height": entry.height,
            "width": entry.width,
            "seed": entry.seed,
            "epoch": t.epoch,
            "population": (
                t.population if t.mode == "resident"
                else int((t.board == 1).sum())
            ),
            "digest": odigest.format_digest(odigest.value(t.lanes)),
            "tiles": len(t.tiles),
            "resident": t.mode == "resident",
        }
        if with_board:
            if board is not None:
                doc["board"] = board.copy()
            elif t.mode == "resident":
                doc["board"] = self._fetch_tiled_board(sid, t)
            else:
                doc["board"] = t.board.copy()
        return doc

    def get(self, sid: str) -> dict:
        with self._lock:
            entry = self.sessions.get(sid)
            if entry is None:
                raise KeyError(sid)
            entry.last_used = time.monotonic()
            t = self.tiled.get(sid)
        if t is not None:
            if t.mode == "resident":
                with self._lock:
                    if t.promoting:
                        self._reject(
                            "failover",
                            f"tiled session {sid} is mid-promotion after "
                            f"a worker loss; retry",
                            link=self._tiled_link_locked(sid),
                        )
            with t.steplock:
                return self._tiled_doc(sid, entry, t, with_board=True)
        p = self._submit(
            {"op": "get", "rid": 0, "sid": sid}, sid=sid,
            shard=entry.shard, kind="get",
        )
        self._await(p)
        doc = dict(p.result["doc"])
        with self._lock:
            entry.epoch = int(doc.get("epoch", entry.epoch))
            entry.digest = doc.get("digest", entry.digest)
            entry.mark_dirty(time.monotonic())
        return doc

    def list(self) -> List[dict]:
        with self._lock:
            return [
                e.summary(
                    None if e.shard is None else self.shard_owner.get(e.shard)
                )
                for e in self.sessions.values()
            ]

    def tenant_of(self, sid: str) -> Optional[str]:
        """Tenant attribution for the SLO access log (None = unknown sid;
        the edge falls back to the default tenant)."""
        with self._lock:
            e = self.sessions.get(sid)
            return e.tenant if e is not None else None

    def canary_targets(self) -> Dict[str, int]:
        """worker name -> one shard it owns, covering every placeable
        member — the canary prober pins one known-orbit session per worker
        by mining a sid whose crc32 hash lands on that shard.  Members
        owning nothing yet get a shard assigned (round-robin through the
        unowned pool), so a fresh cluster is probe-covered immediately."""
        with self._lock:
            targets: Dict[str, int] = {}
            for shard, owner in self.shard_owner.items():
                if owner is not None and owner not in targets:
                    targets[owner] = shard
            members = [
                m.name for m in self.membership.placeable_members()
                if m.name not in targets
            ]
            free = [s for s, o in self.shard_owner.items() if o is None]
            assigned = False
            for name, shard in zip(sorted(members), free):
                self.shard_owner[shard] = name
                targets[name] = shard
                assigned = True
            if assigned:
                self._rebuild_routes_locked()
            return targets

    def delete(self, sid: str) -> None:
        with self._lock:
            entry = self.sessions.get(sid)
            if entry is None:
                raise KeyError(sid)
            if entry.kind == "tiled":
                t = self.tiled.pop(sid, None)
                del self.sessions[sid]
                self._cells -= entry.height * entry.width
                self._m_tiled.set(len(self.tiled))
                if t is not None and t.mode == "resident":
                    self._drop_tiled_locked(sid, t)
                return
        p = self._submit(
            {"op": "delete", "rid": 0, "sid": sid}, sid=sid,
            shard=entry.shard, kind="delete",
        )
        self._await(p)
        with self._lock:
            if self.sessions.get(sid) is entry:
                del self.sessions[sid]
                self._cells -= entry.height * entry.width
                # The replica's standby copy must go too, or a later
                # promotion would resurrect a deleted board.
                self._replicate_forget_locked(entry.shard, sid)

    def step(self, sid: str, steps: int = 1) -> Tuple[int, int]:
        # The steady-state hot path: session lookup and the draining gate
        # read GIL-atomic state without the plane lock — the only lock
        # holds left on a routed step are the (short) enqueue in _submit
        # and the epoch write-back below.
        if steps < 1:
            raise ValueError(f"steps {steps} must be >= 1")
        entry = self.sessions.get(sid)  # graftlint: waive GL-LOCK01 -- hot-path read: a single dict.get is GIL-atomic, and every later mutation re-validates under the lock
        if entry is None:
            raise KeyError(sid)
        if self._draining:  # graftlint: waive GL-LOCK01 -- monotonic one-way bool; the worst stale read admits one op that drains with the rest
            self._reject("draining", "cluster serve plane is draining")
        entry.last_used = time.monotonic()
        if entry.kind == "tiled":
            return self._step_tiled(sid, entry, steps)
        p = self._submit(
            {"op": "step", "rid": 0, "sid": sid, "steps": int(steps)},
            sid=sid, shard=entry.shard, kind="step",
        )
        self._await(p, grace=True)
        qw = p.result.get("qw")
        if qw is not None:
            # Relay the worker-side queue wait to the HTTP edge (the SLO
            # access log separates queueing from compute on this thread).
            _slo.note_queue_wait(float(qw))
        epoch, digest = int(p.result["epoch"]), int(p.result["digest"])
        with self._lock:
            if self.sessions.get(sid) is entry and epoch >= entry.epoch:
                entry.epoch = epoch
                entry.digest = odigest.format_digest(digest)
                entry.mark_dirty(time.monotonic())
        return epoch, digest

    # -- op plumbing ----------------------------------------------------------

    def _rebuild_routes_locked(self) -> None:
        """Publish a fresh immutable routing snapshot (caller holds the
        lock).  Called from every site that changes shard ownership, the
        in-flight move set, or the promotion set — the fast path routes
        entirely from this object and revalidates its identity under one
        short lock hold, so a stale read can never enqueue onto a frozen
        or promoted shard."""
        self._routes = (
            {s: o for s, o in self.shard_owner.items() if o is not None},
            frozenset(self._promoting) | frozenset(
                k for k in self.rebalancer.inflight if isinstance(k, int)
            ),
        )

    def _submit(self, op: dict, *, sid=None, shard=None, kind="",
                member=None, on_done=None) -> _Pending:
        rid = next(self._rids)  # itertools.count is GIL-atomic
        op["rid"] = rid
        if self._trace:
            # Stamp the caller's active span (the HTTP thread's
            # serve.request) onto the op: the worker opens its serve.batch
            # span as a CHILD of this ctx, so one trace spans processes.
            # Cost with no active span: one thread-local read.
            sp = current()
            if sp is not None:
                op[TRACE_KEY] = sp.ctx
        p = _Pending(rid, op, sid=sid, shard=shard, kind=kind,
                     member=member, on_done=on_done)
        if member is None and shard is not None:
            # Fast path: resolve the owner from the immutable snapshot
            # outside the lock; one short hold enqueues, with an identity
            # re-check so a concurrent table change falls back to the
            # full router (which sees the new world).
            routes = self._routes
            owner = routes[0].get(shard)
            if owner is not None and shard not in routes[1]:
                p.member = owner
                with self._lock:
                    if self._routes is routes:
                        self._pending[rid] = p
                        self._outq.setdefault(owner, deque()).append(p)
                        self._wake.set()
                        return p
                p.member = None  # table moved under us: route slowly
        with self._lock:
            self._route_locked(p)
            self._wake.set()
        return p

    def _route_locked(self, p: _Pending) -> None:
        """Aim one op: direct-target ops go straight to their member's
        queue; shard ops go to the shard's owner — or into the held list
        while the shard is mid-migration (replayed at the new owner on
        commit, at the old one on abort: zero admitted ops lost)."""
        self._pending[p.rid] = p
        if p.member is not None:
            self._outq.setdefault(p.member, deque()).append(p)
            return
        if p.shard in self._promoting:
            # The shard's primary just died and its replica is being
            # promoted: EVERY op (step/get/delete/create) answers the
            # retryable 429 ``failover`` — the 404-vs-retryable
            # distinction is the client contract (the board provably
            # resumes at its replicated epoch; a retry lands post-commit).
            del self._pending[p.rid]
            self._reject(
                "failover",
                f"shard {p.shard} is mid-promotion after a worker loss; "
                f"the board resumes at its last replicated epoch — retry",
                link=self._promoting[p.shard].get("span"),
            )
        if p.shard in self.rebalancer.inflight:
            self._held.setdefault(p.shard, []).append(p)
            return
        owner = self.shard_owner.get(p.shard)
        if owner is None:
            owner = self._assign_shard_locked(p.shard)
            if owner is None:
                del self._pending[p.rid]
                self._reject(
                    "no_workers",
                    "no serve workers available for this shard; retry",
                )
        p.member = owner
        self._outq.setdefault(owner, deque()).append(p)

    def _assign_shard_locked(self, shard: int) -> Optional[str]:
        """First placement of an unowned (or orphaned-empty) shard: the
        least-shard-loaded placeable member."""
        members = self.membership.placeable_members() or (
            self.membership.alive_members()
        )
        if not members:
            return None
        loads = {m.name: 0 for m in members}
        for owner in self.shard_owner.values():
            if owner in loads:
                loads[owner] += 1
        name = min(loads, key=lambda n: (loads[n], n))
        self.shard_owner[shard] = name
        self._rebuild_routes_locked()
        return name

    def _await(self, p: _Pending, *, grace: bool = False):
        """Block the caller on its op with the PR 7 timeout contract: an
        op cancelled UNSENT provably never ran (safe retry); a sent op
        gets bounded grace, then reports with outcome unknown."""
        if not p.event.wait(JOB_TIMEOUT_S):
            with self._lock:
                cancelled = False
                if not p.sent and self._pending.pop(p.rid, None) is not None:
                    q = self._outq.get(p.member)
                    if q is not None and p in q:
                        q.remove(p)
                    held = self._held.get(p.shard)
                    if held is not None and p in held:
                        held.remove(p)
                    cancelled = True
            if cancelled:
                raise TimeoutError(
                    f"serve op for {p.sid} timed out unsent (cancelled; "
                    f"not applied)"
                )
            if not p.event.wait(JOB_GRACE_S if grace else 1.0):
                raise TimeoutError(
                    f"serve op for {p.sid} timed out in flight on "
                    f"{p.member}"
                )
        if p.error is not None:
            raise p.error
        return p.result

    def _resolve(self, p: _Pending, *, result=None, error=None) -> None:
        """Complete one op — caller must NOT hold the plane lock (the
        callback path can send frames)."""
        p.result = result
        p.error = error
        p.event.set()
        if p.on_done is not None:
            try:
                p.on_done(p)
            except Exception:  # noqa: BLE001 — internal-callback bug must not kill a reader thread
                pass

    @staticmethod
    def _entry_error(entry: dict) -> BaseException:
        kind = entry.get("err")
        detail = str(entry.get("detail", ""))
        if kind == "admission":
            return AdmissionError(str(entry.get("reason", "unknown")), detail)
        return {
            "key": KeyError,
            "value": ValueError,
            "timeout": TimeoutError,
        }.get(kind, RuntimeError)(detail)

    # -- wire-in (frontend reader threads) ------------------------------------

    def on_result(self, member_name: str, msg: dict) -> None:
        for entry in msg.get("results", []):
            try:
                rid = int(entry["rid"])
            except (KeyError, TypeError, ValueError):
                continue
            with self._lock:
                p = self._pending.pop(rid, None)
            if p is None:
                continue  # cancelled / already failed by member loss
            if entry.get("ok"):
                self._resolve(p, result=entry)
                continue
            err = self._entry_error(entry)
            if (
                isinstance(err, AdmissionError)
                and err.reason == "migrating"
                and p.retries < OP_MAX_RETRIES
            ):
                # The worker froze this shard before our frame arrived:
                # re-route — the held list (or the post-commit owner)
                # replays it, the tenant never sees the reason.  A
                # re-route can itself refuse (last worker just died):
                # that failure must resolve the op, never escape into
                # the frontend's reader thread.
                try:
                    with self._lock:
                        p.retries += 1
                        p.sent = False
                        p.member = None
                        self._route_locked(p)
                        self._wake.set()
                    continue
                except AdmissionError as e:
                    err = e
            self._resolve(p, error=err)

    # -- the flusher (PR 4 coalescing, op-plane edition) ----------------------

    def _enqueue_ctrl_locked(self, member: str, msg: dict) -> _Pending:
        """Queue a raw control frame (SHARD_PREPARE/COMMIT/ABORT) through
        the member's op lane — caller holds the lock.  EVERY shard-control
        frame rides this one FIFO, which is the protocol's whole ordering
        story: a create queued toward the old owner before a migration
        began reaches it before the freeze; an abort can never overtake
        its own prepare and leave sessions frozen forever; a ghost-cleanup
        drop can never overtake the adopt it compensates."""
        if self._trace and TRACE_KEY not in msg:
            # shard_*/replicate control frames join the active trace (a
            # promotion's acks, a migration's prepare/commit) when one is
            # open on this thread — ambient plumbing stays unlinked.
            sp = current()
            if sp is not None:
                msg[TRACE_KEY] = sp.ctx
        p = _Pending(0, msg, kind="ctrl", member=member)
        self._outq.setdefault(member, deque()).append(p)
        self._wake.set()
        return p

    def _flush_loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.25)
            self._wake.clear()
            with self._lock:
                if self._stopped:
                    return
                batches: List[Tuple[str, List[_Pending]]] = []
                for name, q in self._outq.items():
                    if q:
                        ops = list(q)
                        q.clear()
                        for p in ops:
                            p.sent = True
                        batches.append((name, ops))
            for name, entries in batches:
                m = self.membership.get(name)
                if m is None or not m.alive:
                    self._fail_worker_ops(
                        name, [p for p in entries if p.kind != "ctrl"]
                    )
                    continue
                # Coalesce runs of ops into SERVE_OPS frames, emitting
                # interleaved ctrl frames in place so queue order IS wire
                # order (the shard-prepare ordering guarantee).
                run: List[_Pending] = []

                def flush_run(member=m):
                    if run:
                        self._m_frames.inc()
                        self._m_ops.inc(len(run))
                        frame = {
                            "type": P.SERVE_OPS,
                            "ops": [p.op for p in run],
                        }
                        # The PR 2 wire discipline, serve edition: the
                        # frame itself carries the FIRST traced op's ctx
                        # (each op still carries its own — a coalesced
                        # frame spans many requests).
                        for p in run:
                            ctx = p.op.get(TRACE_KEY)
                            if ctx is not None:
                                frame[TRACE_KEY] = dict(ctx)
                                break
                        self._send_to(member, frame)
                        run.clear()

                for p in entries:
                    if p.kind == "ctrl":
                        flush_run()
                        self._send_to(m, p.op)
                    else:
                        run.append(p)
                flush_run()

    def _reroute_unsent_locked(
        self, p: _Pending, name: str
    ) -> Optional[BaseException]:
        """One UNSENT op aimed at dead ``name`` (caller holds the lock
        and has popped it from ``_pending``): re-route what provably
        never ran — creates re-hash to the shard's new owner, pure tile
        chunks re-pick any worker — and return the error everything else
        must resolve with (None = re-routed).  The ONE implementation of
        this contract: the flusher's dead-member path and the membership
        hook must not drift."""
        if p.kind in ("create", "tile") and p.retries < OP_MAX_RETRIES:
            p.retries += 1
            p.sent = False
            if p.kind == "tile":
                p.member = self._pick_worker_locked()
                if p.member is None:
                    return AdmissionError(
                        "no_workers", "no serve workers left"
                    )
            else:
                p.member = None
            try:
                self._route_locked(p)
                return None
            except AdmissionError as e:
                return e
        return TimeoutError(
            f"serve worker {name} lost before this op ran; retry"
        )

    def _fail_worker_ops(self, name: str, ops: List[_Pending]) -> None:
        """Ops aimed at a member that died before the frame went out:
        unsent work provably never ran — re-route what can move (creates,
        tile chunks), fail the rest retryably."""
        dead: List[Tuple[_Pending, BaseException]] = []
        with self._lock:
            for p in ops:
                self._pending.pop(p.rid, None)
                err = self._reroute_unsent_locked(p, name)
                if err is not None:
                    dead.append((p, err))
            self._wake.set()
        for p, err in dead:
            self._resolve(p, error=err)

    # -- membership hooks (called by the frontend) ----------------------------

    def on_member_joined(self, name: str) -> None:
        """A worker joined: claim any unowned shards for it (first worker
        takes the whole table; later joiners receive shards through the
        rebalancer — empty ones flip instantly, loaded ones migrate)."""
        with self._lock:
            if self._stopped:
                return
            unowned = [s for s, o in self.shard_owner.items() if o is None]
            for shard in unowned:
                self._assign_shard_locked(shard)
            # The joiner may be the FIRST second worker: single-copy
            # shards get their replica (and the primaries a stream reset)
            # right away, not at the next maintenance pass.
            self._refresh_replicas_locked()
        self._refresh_gauges()

    def on_member_lost(self, name: str) -> None:
        """A worker died.  Shards with a live replica PROMOTE — their
        sessions survive, resuming from the last acked replicated epoch,
        and ops caught in the window answer the retryable 429
        ``failover``.  Shards without one lose their sessions honestly
        (404 + ``gol_serve_sessions_lost_total``).  Every in-flight op
        gets an ANSWER (the never-silently-lost contract): sent ops on
        promoting shards answer ``failover``, other sent ops report
        unknown-outcome, unsent creates/tile-chunks replay elsewhere,
        ops for dead sessions 404.  Migrations involving the member roll
        back or — when the certified state already left the source —
        complete anyway."""
        resolutions: List[Tuple[_Pending, Optional[dict], Optional[BaseException]]] = []
        aborts: List = []
        promotions: List[Tuple[int, dict]] = []
        tiled_plans: List[tuple] = []
        with self._lock:
            if self._stopped:
                return  # teardown: member losses are expected, plane is done
            tiled_plans = self._begin_tiled_promotions_locked(name)
            doomed = self.rebalancer.drop_member(name)
            for mig in doomed:
                phase = getattr(mig, "phase", "prepare")
                if mig.source == name and phase == "adopt":
                    # The certified payload already left the dead source:
                    # the in-flight adopt at the (live) dest completes the
                    # move and the sessions SURVIVE their worker's death.
                    continue
                aborts.append((mig, "member_lost",
                               mig.source != name, mig.source == name))
            lost_shards = [
                s for s, o in self.shard_owner.items()
                if o == name and s not in self.rebalancer.inflight
            ]
            lost_sids: set = set()
            for shard in lost_shards:
                info = self._begin_promotion_locked(shard)
                if info is not None:
                    promotions.append((shard, info))
                    lost_sids |= info["dropped"]
                    continue
                # No live replica (replication off, single-copy shard, or
                # a double failure): honest loss.
                for sid in [
                    s for s, e in self.sessions.items() if e.shard == shard
                ]:
                    e = self.sessions.pop(sid)
                    self._cells -= e.height * e.width
                    self._m_sessions_lost.inc()
                    lost_sids.add(sid)
                self.shard_owner[shard] = None
                self._assign_shard_locked(shard)
            promoting = set(self._promoting)
            for p in list(self._pending.values()):
                if p.member != name:
                    continue
                self._pending.pop(p.rid, None)
                if p.shard in promoting:
                    # The board provably resumes at its replicated epoch:
                    # retryable, never an unknown-outcome shrug.  The 429
                    # links to the promotion span that caused it.
                    info = self._promoting.get(p.shard)
                    span = info.get("span") if info is not None else None
                    resolutions.append((p, None, AdmissionError(
                        "failover",
                        f"serve worker {name} lost mid-op; the shard's "
                        f"replica is being promoted — retry",
                        trace_link=span.ctx if span is not None else None,
                    )))
                elif p.sent:
                    resolutions.append((p, None, TimeoutError(
                        f"serve worker {name} lost; op outcome unknown"
                        + (" (session lost with it)" if p.sid in lost_sids
                           else "")
                    )))
                elif p.sid in lost_sids:
                    resolutions.append((p, None, KeyError(p.sid)))
                else:
                    q = self._outq.get(name)
                    if q is not None and p in q:
                        q.remove(p)
                    err = self._reroute_unsent_locked(p, name)
                    if err is not None:
                        resolutions.append((p, None, err))
            self._outq.pop(name, None)
            # A dead member may also have been a REPLICA: re-home every
            # replica assignment that pointed at it (the primaries get a
            # reset, so their streams start from scratch toward the new
            # replica).
            self._refresh_replicas_locked()
            self._wake.set()
        for mig, reason, notify, lost in aborts:
            self._abort_shard(mig, reason, source_alive=notify,
                              sessions_lost=lost)
        for shard, info in promotions:
            self._launch_promotion(shard, info, lost_member=name)
        for plan in tiled_plans:
            self._launch_tiled_promotion(plan, lost_member=name)
        for p, result, error in resolutions:
            self._resolve(p, result=result, error=error)
        # Gauge reclaim, the heartbeat-age discipline: a dead member's
        # series must read zero, not its last live value.
        self._m_shards.labels(member=name).set(0)
        self._m_shard_sessions.labels(member=name).set(0)
        self._m_wqueue.labels(member=name).set(0)

    def member_clear(self, name: str) -> bool:
        """May a draining member be released?  Only once it owns no
        shards, no migration involves it, and nothing is queued toward
        it — the serve analog of 'owns no tiles'."""
        with self._lock:
            if any(o == name for o in self.shard_owner.values()):
                return False
            if any(r == name for r in self.shard_replica.values()):
                # Still a replica somewhere: releasing it now would
                # silently drop standby state the re-homing pass (drains
                # re-home replicas every poll) hasn't moved yet.
                return False
            if any(
                info["dest"] == name for info in self._promoting.values()
            ):
                return False
            if any(
                name in (m.source, m.dest)
                for m in self.rebalancer.inflight.values()
            ):
                return False
            for t in self.tiled.values():
                if t.mode != "resident":
                    continue
                if any(o == name for o in t.owner.values()):
                    return False  # still hosts resident chunks
                if any(r == name for r in t.replica.values()):
                    return False  # still a chunk replica
            if any(
                name in (m.source, m.dest)
                for m in self.tiled_rebalancer.inflight.values()
            ):
                return False
            q = self._outq.get(name)
            if q:
                return False
            return not any(
                p.member == name for p in self._pending.values()
            )

    # -- shard migration (frontend side) --------------------------------------

    def poll(self, now: float, drain_only: bool = False) -> None:
        """One maintenance pass: expire overdue moves, plan new ones
        (drain evacuation always; load spreading cadenced), sweep the
        idle-session TTL, refresh the per-worker gauges."""
        with self._lock:
            if self._stopped:
                return
            overdue = self.rebalancer.expired(now)
        for mig in overdue:
            self._abort_shard(mig, "deadline")
        self._sweep_ttl(now)
        lag_events: Dict[int, float] = {}
        with self._lock:
            if self._stopped or self._draining:
                self._refresh_gauges_locked()
                return
            # Replica upkeep before planning: drains re-home replicas
            # (a draining worker is not placeable), losses already
            # re-homed in on_member_lost, and the single-copy gauge
            # tracks the honest degradation level.
            self._refresh_replicas_locked()
            for t in self.tiled.values():
                if t.mode == "resident" and not t.promoting:
                    self._assign_tiled_replicas_locked(t)
            tiled_moves = []
            for key, source, dest in self._plan_tiled_moves_locked(
                now, drain_only
            ):
                mig = self.tiled_rebalancer.begin(key, source, dest, now)
                tiled_moves.append((key, source, dest, mig.seq))
            lag_events = {
                s: self._lag_snapshot.get(s, 0.0)
                for s in self._update_lag_locked(now)
            }
            members = self.membership.alive_members()
            weights: Dict[int, int] = {}
            for e in self.sessions.values():
                if e.shard is not None:
                    weights[e.shard] = weights.get(e.shard, 0) + 1
            plans = self.rebalancer.plan_shards(
                {
                    s: o for s, o in self.shard_owner.items()
                    if o is not None and s not in self._promoting
                },
                weights, members, now, drain_only=drain_only,
                replicas=self.shard_replica,
            )
            for shard, source, dest in plans:
                sids = [
                    sid for sid, e in self.sessions.items()
                    if e.shard == shard
                ]
                busy = any(
                    p.shard == shard for p in self._pending.values()
                )
                if not sids and not busy:
                    # Empty shard: ownership flips without any protocol —
                    # this is how a late joiner starts receiving shards
                    # the moment the planner notices it.
                    self.shard_owner[shard] = dest
                    self._rebuild_routes_locked()
                    continue
                mig = self.rebalancer.begin(shard, source, dest, now)
                self._rebuild_routes_locked()
                mig.phase = "prepare"
                mig.sids = sids  # plan-time estimate; the WORKER's export
                # is authoritative (it recomputes membership by hash when
                # the prepare executes, after every earlier op frame)
                mig.span = self.tracer.start(
                    "serve.shard_migrate", node="frontend",
                    shard=shard, source=source, dest=dest,
                    sessions=len(sids),
                )
                # Queued through the source's op lane (NOT sent directly):
                # wire order against already-routed ops is the correctness
                # of the freeze — see _enqueue_ctrl_locked.
                mig.prepare_pending = self._enqueue_ctrl_locked(source, {
                    "type": P.SHARD_PREPARE, "shard": shard,
                    "seq": mig.seq,
                })
            self._refresh_gauges_locked()
        for key, source, dest, seq in tiled_moves:
            # Each resident-chunk move runs on its own thread: it holds
            # the session's steplock across export → certify → adopt, so
            # the maintenance loop must not block behind it.
            threading.Thread(
                target=self._migrate_tiled_chunk,
                args=(key, source, dest, seq),
                daemon=True, name=f"tiled-move-{key[0]}",
            ).start()
        if self.events is not None:
            for shard, lag in sorted(lag_events.items()):
                # Loud, transition-edged (only shards NEWLY over the
                # bound): replication lag is never silently unbounded.
                self.events.emit(
                    "serve_replication_lag_exceeded", shard=shard,
                    lag_s=round(lag, 3), bound_s=self.repl_max_lag_s,
                )

    def _sweep_ttl(self, now: float) -> None:
        """The cluster-wide idle-session TTL (workers run with ttl 0 —
        eviction must retire the budget charged HERE, or idle sessions
        would leak serve_max_cells forever).  Tiled sessions drop in
        place; batch sessions retire through a real delete op so the
        worker table and this index let go together."""
        if self.ttl_s <= 0:
            return
        evict_ops: List[Tuple[str, int]] = []
        with self._lock:
            for sid, e in list(self.sessions.items()):
                if (
                    e.evicting
                    or now - e.last_used <= self.ttl_s
                    or (e.shard is not None
                        and e.shard in self.rebalancer.inflight)
                ):
                    continue
                if e.kind == "tiled":
                    t = self.tiled.pop(sid, None)
                    if t is not None and t.mode == "resident":
                        if t.promoting:
                            self.tiled[sid] = t
                            continue  # settle the promotion first
                        self._drop_tiled_locked(sid, t)
                    del self.sessions[sid]
                    self._cells -= e.height * e.width
                    self._m_tiled.set(len(self.tiled))
                    self._m_evictions.inc()
                else:
                    e.evicting = True
                    evict_ops.append((sid, e.shard))
        for sid, shard in evict_ops:
            try:
                self._submit(
                    {"op": "delete", "rid": 0, "sid": sid},
                    sid=sid, shard=shard, kind="evict",
                    on_done=lambda p, sid=sid: self._on_evicted(sid, p),
                )
            except (AdmissionError, KeyError, RuntimeError):
                # No worker / plane closing: clear the mark so the next
                # sweep retries instead of pinning the entry forever.
                with self._lock:
                    e = self.sessions.get(sid)
                    if e is not None:
                        e.evicting = False

    def _on_evicted(self, sid: str, p: _Pending) -> None:
        """An eviction delete answered.  Deleted (or already gone
        worker-side) → release the index entry and its budget; any other
        failure → unmark, the next sweep retries."""
        with self._lock:
            e = self.sessions.get(sid)
            if e is None:
                return
            if p.error is None or isinstance(p.error, KeyError):
                del self.sessions[sid]
                self._cells -= e.height * e.width
                self._m_evictions.inc()
                self._replicate_forget_locked(e.shard, sid)
            else:
                e.evicting = False

    def on_shard_state(self, member_name: str, msg: dict) -> None:
        """TRANSFER → CERTIFY → adopt-at-dest → COMMIT.  Exactly the tile
        protocol's shape: every session payload re-derives its digest
        lanes (``digest_payload_np``) before any ownership change; a
        mismatch rolls back loudly and the source (which never dropped the
        sessions) unfreezes."""
        shard = int(msg["shard"])
        seq = int(msg["seq"])
        with self._lock:
            mig = self.rebalancer.get(shard, seq)
            if mig is None or mig.source != member_name:
                return  # stale frame from an aborted attempt
        if msg.get("error"):
            self._abort_shard(mig, f"source: {msg['error']}")
            return
        payloads = msg.get("sessions", [])
        # Certification OUTSIDE the lock: O(session bytes) per board.
        for pay in payloads:
            lanes = odigest.digest_payload_np(
                pay["state"], (0, 0), int(pay["width"])
            )
            self._m_digest_checks.inc()
            if [int(lanes[0]), int(lanes[1])] != [
                int(v) for v in pay["digest"]
            ]:
                self._m_digest_mismatches.inc()
                if self.events is not None:
                    self.events.emit(
                        "serve_shard_digest_mismatch",
                        shard=shard, sid=pay.get("sid"), source=member_name,
                    )
                self._abort_shard(mig, "digest_mismatch")
                return
        with self._lock:
            if self.rebalancer.get(shard, seq) is not mig:
                return  # aborted while certifying
            dest = self.membership.get(mig.dest)
            if dest is None or not dest.alive:
                dest = None
            else:
                mig.phase = "adopt"
                mig.payload_sids = [p["sid"] for p in payloads]
                # Submitted under the SAME lock acquisition that set the
                # phase (RLock; _submit only enqueues): an abort racing
                # this window must always see adopt_pending, or it could
                # neither recall the adopt nor clean up after it.
                mig.adopt_pending = self._submit(
                    {"op": "adopt", "rid": 0, "sessions": payloads},
                    kind="adopt", member=dest.name,
                    on_done=lambda p, mig=mig: self._on_adopted(mig, p),
                )
        if dest is None:
            self._abort_shard(mig, "dest_lost")
            return

    def _on_adopted(self, mig, p: _Pending) -> None:
        if p.error is not None:
            self._abort_shard(mig, f"adopt failed: {p.error!r}")
            return
        flush: List[_Pending] = []
        with self._lock:
            if self.rebalancer.get(mig.tile, mig.seq) is not mig:
                return
            self.rebalancer.complete(mig.tile)
            self.shard_owner[mig.tile] = mig.dest
            self._rebuild_routes_locked()
            self._m_migrations.inc()
            if mig.span is not None:
                mig.span.set(outcome="commit").finish()
                mig.span = None
            src = self.membership.get(mig.source)
            if src is not None and src.alive:
                # Through the source's op lane, like the prepare: every
                # shard-control frame for one worker rides ONE FIFO, so
                # no control message can ever overtake another.
                self._enqueue_ctrl_locked(mig.source, {
                    "type": P.SHARD_COMMIT, "shard": mig.tile,
                    "sids": getattr(mig, "payload_sids", mig.sids),
                })
            for held in self._held.pop(mig.tile, []):
                held.member = mig.dest
                held.sent = False
                self._outq.setdefault(mig.dest, deque()).append(held)
                flush.append(held)
            # Ownership moved: the replica may now co-reside with the new
            # owner (it was the migration dest's sibling constraint, but
            # membership may have shifted) — reconcile immediately, so the
            # co-residence window is one lock hold, not one poll tick.
            self._refresh_replicas_locked()
            self._wake.set()
        if self.events is not None:
            self.events.emit(
                "serve_shard_migrated", shard=mig.tile,
                source=mig.source, dest=mig.dest,
                sessions=len(getattr(mig, "payload_sids", [])),
                replayed_ops=len(flush),
            )

    def _abort_shard(
        self, mig, reason: str, *, source_alive: bool = True,
        sessions_lost: bool = False,
    ) -> None:
        """Roll a shard move back.  ``sessions_lost`` (dead source before
        transfer): the shard's sessions died with their worker — index
        entries release, held writes 404, held creates re-route."""
        resolutions: List[Tuple[_Pending, BaseException]] = []
        with self._lock:
            if self.rebalancer.get(mig.tile, mig.seq) is not mig:
                return
            self.rebalancer.abort(mig.tile, time.monotonic())
            self._rebuild_routes_locked()
            self._m_migration_aborts.inc()
            # An abort racing the adopt phase (deadline mid-install, dest
            # flapping) must not strand GHOST session copies at the
            # destination while the unfrozen source keeps serving: an
            # adopt still in the queue is recalled; otherwise a drop of
            # the same sids rides the dest's SAME op lane — the one FIFO
            # guarantees it lands after the adopt whatever the flusher
            # was doing when the abort fired (p.sent alone cannot tell:
            # the flusher marks it before the frame is actually written).
            ap = getattr(mig, "adopt_pending", None)
            if ap is not None:
                self._pending.pop(ap.rid, None)
                q = self._outq.get(ap.member)
                if q is not None and ap in q:
                    q.remove(ap)
                else:
                    dst = self.membership.get(mig.dest)
                    if dst is not None and dst.alive:
                        self._enqueue_ctrl_locked(mig.dest, {
                            "type": P.SHARD_COMMIT, "shard": mig.tile,
                            "sids": getattr(mig, "payload_sids", mig.sids),
                        })
            if mig.span is not None:
                mig.span.set(outcome="abort", reason=reason).finish()
                mig.span = None
            # A source that died mid-protocol means the shard's sessions
            # died with it even when the CALLER didn't know that (e.g. the
            # member-loss path let an in-flight adopt run on, and the
            # adopt then failed): without this, shard_owner would point at
            # the dead member forever — membership already evicted it, so
            # nothing else would ever reassign the shard — wedging every
            # future op for 1/serve_shards of the keyspace.
            src_m = self.membership.get(mig.source)
            lost = sessions_lost or src_m is None or not src_m.alive
            promotion = None
            if lost:
                # A source that died mid-migration is just a worker loss
                # wearing a migration: a live replica PROMOTES — the op
                # FIFO makes the race safe (the promote lands at the
                # replica after every replicate install already queued,
                # and the recalled adopt/cleanup rides the dest's own
                # lane) — and only a replica-less shard loses sessions.
                promotion = self._begin_promotion_locked(mig.tile)
                if promotion is None:
                    # Recomputed LIVE from the index (not the plan-time
                    # snapshot): a create that landed on the shard after
                    # the migration was planned died with the source too.
                    for sid in [
                        s for s, e in self.sessions.items()
                        if e.shard == mig.tile
                    ]:
                        e = self.sessions.pop(sid)
                        self._cells -= e.height * e.width
                        self._m_sessions_lost.inc()
                    self.shard_owner[mig.tile] = None
                    self._assign_shard_locked(mig.tile)
            held = self._held.pop(mig.tile, [])
            for p in held:
                self._pending.pop(p.rid, None)
                if lost and promotion is not None:
                    # Mid-promotion: the retryable contract, never a 404
                    # for a board that provably survives.
                    pspan = promotion.get("span")
                    resolutions.append((p, AdmissionError(
                        "failover",
                        f"shard {mig.tile} is being promoted after its "
                        f"worker died mid-migration; retry",
                        trace_link=pspan.ctx if pspan is not None else None,
                    )))
                elif lost and p.kind != "create":
                    resolutions.append((p, KeyError(p.sid)))
                else:
                    # Replay at whoever owns the shard now (the unfrozen
                    # source on a plain abort; a survivor on source loss).
                    p.sent = False
                    p.member = None
                    try:
                        self._route_locked(p)
                    except AdmissionError as e:
                        resolutions.append((p, e))
            if source_alive and not lost:
                # A prepare still sitting in the queue is simply recalled
                # (no freeze will ever happen); otherwise the abort rides
                # the SAME lane, so it always lands after the freeze it
                # undoes and the worker unfreezes the set IT froze.
                pp = getattr(mig, "prepare_pending", None)
                q = self._outq.get(mig.source)
                if pp is not None and q is not None and pp in q:
                    q.remove(pp)
                else:
                    self._enqueue_ctrl_locked(mig.source, {
                        "type": P.SHARD_ABORT, "shard": mig.tile,
                    })
            self._wake.set()
        if self.events is not None:
            self.events.emit(
                "serve_shard_migration_aborted", shard=mig.tile,
                source=mig.source, dest=mig.dest, reason=reason,
            )
        for p, err in resolutions:
            self._resolve(p, error=err)
        if promotion is not None:
            self._launch_promotion(
                mig.tile, promotion, lost_member=mig.source
            )

    # -- session replication & failover ---------------------------------------

    def _replica_for_locked(
        self, shard: int, owner: Optional[str], names: List[str],
        current: Optional[str] = None,
    ) -> Optional[str]:
        """The shard's replica — STICKY first, rendezvous-hashed second,
        never the primary.  A still-valid current replica is kept: every
        reassignment discards acked standby state and resets the stream,
        so churn IS a board-loss window (a primary dying before the new
        replica's from-scratch stream acks loses what the old replica
        still held).  Fresh assignments use rendezvous hashing
        (highest-random-weight by (shard, worker)), so a membership
        change re-homes only the shards that must move, not ~all of them
        the way a modulo ring would."""
        if not self._replicate or owner is None:
            return None
        if current is not None and current != owner and current in names:
            return current
        return rendezvous_pick(str(shard), (n for n in names if n != owner))

    def _refresh_replicas_locked(self) -> None:
        """Reconcile replica assignments with the current membership and
        shard table (caller holds the lock).  A change resets the
        frontend watermarks for the shard, tells the primary to restart
        its stream from scratch (the new replica holds nothing), and
        tells a surviving old replica to drop its standby copies.  Also
        refreshes the single-copy gauge — the honest-degradation signal."""
        now = time.monotonic()
        names = sorted(
            m.name for m in self.membership.placeable_members()
        )
        single = 0
        for shard, owner in self.shard_owner.items():
            if shard in self._promoting:
                continue  # ownership settles at the promote result first
            desired = self._replica_for_locked(
                shard, owner, names, current=self.shard_replica.get(shard)
            )
            if owner is not None and desired is None and self._replicate:
                single += 1
            cur = self.shard_replica.get(shard)
            if desired == cur:
                continue
            self.shard_replica[shard] = desired
            if cur is not None:
                old = self.membership.get(cur)
                if old is not None and old.alive:
                    self._submit(
                        {"op": "replica_drop", "rid": 0, "shard": shard},
                        kind="replicate", member=cur,
                        on_done=lambda _p: None,
                    )
            # The new replica starts empty: frontend watermarks reset and
            # the primary streams the shard from scratch.
            for e in self.sessions.values():
                if e.shard == shard:
                    e.repl_epoch = -1
                    if e.repl_dirty_since is None:
                        e.repl_dirty_since = now
            if owner is not None:
                pm = self.membership.get(owner)
                if pm is not None and pm.alive:
                    self._enqueue_ctrl_locked(owner, {
                        "type": P.SHARD_REPLICATE_ACK, "shard": shard,
                        "reset": True,
                    })
        self._m_single_copy.set(single if self._replicate else 0)

    def _replicate_forget_locked(self, shard, sid: str) -> None:
        """A session left the index (delete/evict): its replica standby
        copy must go too, or a later promotion would resurrect it."""
        if not self._replicate or shard is None:
            return
        repl = self.shard_replica.get(shard)
        m = self.membership.get(repl) if repl is not None else None
        if m is None or not m.alive:
            return
        self._submit(
            {"op": "replicate", "rid": 0, "shard": int(shard),
             "sessions": [], "deleted": [sid]},
            kind="replicate", member=repl, on_done=lambda _p: None,
        )

    def on_shard_replicate(self, member_name: str, msg: dict) -> None:
        """A primary's replication stream frame: relay the payloads to
        the shard's replica through the replica's op FIFO (so an install
        can never reorder against a promote/adopt there), or park the
        stream when no replica is placeable."""
        if not self._replicate:
            return
        if "tiled" in msg:
            # Resident tiled-chunk snapshots share the frame kind but are
            # keyed by (sid, chunk), not shard.
            self.on_tiled_replicate(member_name, msg["tiled"])
            return
        shard = int(msg["shard"])
        payloads = msg.get("sessions", [])
        with self._lock:
            if self._stopped:
                return
            if (
                self.shard_owner.get(shard) != member_name
                or shard in self._promoting
            ):
                return  # stale stream from a former owner; ignore
            repl = self.shard_replica.get(shard)
            m = self.membership.get(repl) if repl is not None else None
            if m is None or not m.alive:
                # Single-copy mode: park the primary's stream instead of
                # letting it re-ship every board every pass to nobody.
                self._enqueue_ctrl_locked(member_name, {
                    "type": P.SHARD_REPLICATE_ACK, "shard": shard,
                    "parked": True,
                })
                return
            # A session deleted mid-stream must not resurrect standby-side.
            keep = [
                pay for pay in payloads
                if (e := self.sessions.get(str(pay.get("sid")))) is not None
                and e.shard == shard
            ]
            if not keep:
                return
            nbytes = 0
            for pay in keep:
                data = pay.get("state", {}).get("data")
                nbytes += getattr(data, "nbytes", 0)
            self._m_repl_bytes.inc(nbytes)
            self._submit(
                {"op": "replicate", "rid": 0, "shard": shard,
                 "sessions": keep},
                kind="replicate", member=repl,
                on_done=lambda p, shard=shard, primary=member_name: (
                    self._on_replicated(shard, primary, p)
                ),
            )

    def _on_replicated(self, shard: int, primary: str, p: _Pending) -> None:
        """A replica acked an install: advance the frontend watermarks
        and relay the ack to the primary (its op FIFO) so its stream
        moves on.  A failed install is simply NOT acked — the primary's
        next pass retransmits (the watermark-retransmit contract)."""
        if p.error is not None or not p.result:
            return
        acked = {
            str(sid): int(epoch)
            for sid, epoch in dict(p.result.get("acked", {})).items()
        }
        if not acked:
            return
        with self._lock:
            if self.shard_replica.get(shard) != p.member:
                return  # replica reassigned since: a stale ack must not
                # advance watermarks the NEW replica never earned
            now = time.monotonic()
            for sid, epoch in acked.items():
                e = self.sessions.get(sid)
                if e is None or e.shard != shard:
                    continue
                if epoch > e.repl_epoch:
                    e.repl_epoch = epoch
                    # Re-base the lag clock on every watermark advance:
                    # the oldest un-acked update is now at most this old.
                    # Without this, a continuously-stepped session's lag
                    # would read time-since-FIRST-dirty and fire a false
                    # over-bound alert under perfectly healthy sustained
                    # traffic.
                    e.repl_dirty_since = (
                        None if e.repl_epoch >= e.epoch else now
                    )
                elif e.repl_epoch >= e.epoch:
                    e.repl_dirty_since = None
            pm = self.membership.get(primary)
            if pm is not None and pm.alive:
                self._enqueue_ctrl_locked(primary, {
                    "type": P.SHARD_REPLICATE_ACK, "shard": shard,
                    "acked": acked,
                })

    def _begin_promotion_locked(self, shard: int) -> Optional[dict]:
        """Mark one dead-owner shard for promotion (caller holds the
        lock): flip ownership to the live replica, drop the sessions the
        replica never acked (nothing can save them — counted lost), and
        open the ``serve.promote`` span.  Returns the promotion record,
        or None when no live replica exists (the caller takes the honest-
        loss path)."""
        repl = self.shard_replica.get(shard) if self._replicate else None
        m = self.membership.get(repl) if repl is not None else None
        if m is None or not m.alive or shard in self._promoting:
            return None
        dropped: set = set()
        kept = 0
        for sid in [
            s for s, e in self.sessions.items() if e.shard == shard
        ]:
            e = self.sessions[sid]
            if e.repl_epoch < 0:
                del self.sessions[sid]
                self._cells -= e.height * e.width
                self._m_sessions_lost.inc()
                dropped.add(sid)
            else:
                kept += 1
        self.shard_owner[shard] = repl
        self.shard_replica[shard] = None
        self._rebuild_routes_locked()
        info = {
            "dest": repl,
            "t0": time.monotonic(),
            "sessions": kept,
            "dropped": dropped,
            "span": self.tracer.start(
                "serve.promote", node="frontend", shard=shard,
                dest=repl, sessions=kept,
            ),
        }
        self._promoting[shard] = info
        return info

    def _launch_promotion(
        self, shard: int, info: dict, *, lost_member: str = ""
    ) -> None:
        """Fire one promotion (caller must NOT hold the lock): flight
        dump — a promotion is exactly the moment a post-mortem wants
        context for — then the ``promote`` op through the replica's op
        FIFO, ordered after every replicate install already queued
        there."""
        flight = getattr(self.tracer, "flight", None)
        if flight is not None:
            flight.dump("serve_promote", node="frontend")
        if self.events is not None:
            self.events.emit(
                "serve_promotion_started", shard=shard,
                dest=info["dest"], lost=lost_member,
                sessions=info["sessions"],
                unreplicated_lost=len(info["dropped"]),
            )
        try:
            self._submit(
                {"op": "promote", "rid": 0, "shard": shard},
                kind="promote", member=info["dest"],
                on_done=lambda p, shard=shard: self._on_promoted(shard, p),
            )
        except Exception as e:  # noqa: BLE001 — a submit failure must
            # resolve the promotion (double failure), never strand the
            # shard mid-promotion forever
            fake = _Pending(0, {}, kind="promote", member=info["dest"])
            fake.error = e
            self._on_promoted(shard, fake)

    def _on_promoted(self, shard: int, p: _Pending) -> None:
        """The replica answered the promote.  Success: promoted sessions
        resume at their certified replicated epoch (index epochs roll
        BACK to it — that is the honest state), a new replica is
        appointed, and the new primary streams from scratch.  Failure
        (the replica died too — double failure): the shard's remaining
        sessions are lost honestly."""
        lost: List[str] = []
        failed: List[str] = []
        promoted = 0
        with self._lock:
            info = self._promoting.get(shard)
            if info is None or info["dest"] != p.member:
                return
            del self._promoting[shard]
            self._rebuild_routes_locked()
            span = info["span"]
            now = time.monotonic()
            if p.error is not None or not p.result:
                for sid in [
                    s for s, e in self.sessions.items() if e.shard == shard
                ]:
                    e = self.sessions.pop(sid)
                    self._cells -= e.height * e.width
                    self._m_sessions_lost.inc()
                    lost.append(sid)
                if self.shard_owner.get(shard) == p.member:
                    self.shard_owner[shard] = None
                    self._assign_shard_locked(shard)
                if span is not None:
                    span.set(outcome="lost", error=repr(p.error)).finish()
            else:
                installed = {
                    str(row["sid"]): row
                    for row in p.result.get("installed", [])
                }
                failed = [str(s) for s in p.result.get("failed", [])]
                for sid in [
                    s for s, e in self.sessions.items() if e.shard == shard
                ]:
                    e = self.sessions[sid]
                    row = installed.get(sid)
                    if row is None:
                        # Standby missing or digest-refused: lost, loudly.
                        del self.sessions[sid]
                        self._cells -= e.height * e.width
                        self._m_sessions_lost.inc()
                        if sid in failed:
                            self._m_digest_mismatches.inc()
                        lost.append(sid)
                        continue
                    # Certified resume point: the index rolls back to the
                    # replicated epoch — that IS the board's state now.
                    e.epoch = int(row["epoch"])
                    e.digest = odigest.format_digest(odigest.value(
                        np.asarray(row["digest"], dtype=np.uint32)
                    ))
                    e.repl_epoch = -1
                    e.repl_dirty_since = now
                    promoted += 1
                self._m_promotions.inc()
                if span is not None:
                    span.set(
                        outcome="promoted", sessions=promoted,
                        latency_s=round(now - info["t0"], 6),
                    ).finish()
                # Appoint the next replica; the new primary streams the
                # shard from scratch (it has no watermark state).
                self._refresh_replicas_locked()
            self._wake.set()
        if self.events is not None:
            self.events.emit(
                "serve_promotion_finished", shard=shard, dest=p.member,
                promoted=promoted, lost=len(lost),
                digest_refused=len(failed),
                outcome="lost" if p.error is not None else "promoted",
            )

    # -- frontend federation hooks (serve/federation.py) ----------------------

    def control_rows(self) -> List[dict]:
        """This frontend's slice of control state, one row per session —
        what the federation streams to its standby peer frontend.  Batch
        rows promote into placeholder index entries on a confirmed
        frontend death; tiled rows (``shard`` None — the cells live on
        workers) ride as certified-floor observability only."""
        with self._lock:
            return [
                {
                    "sid": sid, "tenant": e.tenant, "kind": e.kind,
                    "rule": e.rule_s, "height": e.height, "width": e.width,
                    "seed": e.seed, "density": e.density, "shard": e.shard,
                    "epoch": e.epoch, "digest": e.digest,
                    "slice": shard_of(sid, self.n_shards),
                }
                for sid, e in self.sessions.items()
            ]

    def begin_federation_promotion(self, rows: List[dict], *,
                                   origin: str) -> None:
        """A peer frontend died and THIS frontend (its rendezvous
        standby) adopted its slices: install the replicated batch rows as
        placeholder index entries and open a federation failover window
        per shard — windowed ops answer retryable 429 ``failover`` (never
        404) until the dead frontend's workers re-home their control
        channel here and announce session truth with ``SHARD_HOME``
        (:meth:`on_shard_home`), or the re-home grace expires
        (:meth:`expire_federation_promotion`)."""
        now = time.monotonic()
        shards: set = set()
        installed = 0
        with self._lock:
            for row in rows:
                if not isinstance(row, dict) or row.get("kind") != "batch":
                    continue  # tiled cells live on workers; nothing to park
                sid = str(row.get("sid", ""))
                if not sid or sid in self.sessions:
                    continue
                shard = shard_of(sid, self.n_shards)
                e = _Entry(
                    sid, str(row.get("tenant", "default")), "batch",
                    str(row.get("rule", "B3/S23")),
                    int(row.get("height", 0)), int(row.get("width", 0)),
                    int(row.get("seed", 0)),
                    float(row.get("density", 0.5)), shard,
                )
                e.epoch = int(row.get("epoch", 0))
                e.digest = row.get("digest")
                e.repl_dirty_since = now
                self.sessions[sid] = e
                self._cells += e.height * e.width
                shards.add(shard)
                installed += 1
            for shard in shards:
                if shard in self._promoting:
                    continue
                self._promoting[shard] = {
                    # dest=None can never collide with a worker name, so
                    # the replica-promotion reply path (_on_promoted's
                    # dest guard) ignores these windows.
                    "fed": True, "dest": None, "origin": origin,
                    "t0": now, "sessions": installed, "dropped": set(),
                    "span": self.tracer.start(
                        "serve.fed_promote", node="frontend", shard=shard,
                        origin=origin,
                    ),
                }
            self._rebuild_routes_locked()
            self._wake.set()
        if self.events is not None:
            self.events.emit(
                "serve_federation_promotion", origin=origin,
                sessions=installed, shards=len(shards),
            )

    def expire_federation_promotion(self, shard: int) -> None:
        """No ``SHARD_HOME`` arrived within the re-home grace — the dead
        frontend's workers died with it.  Close the window honestly: the
        placeholder sessions are lost (counted, evented), and the shard
        reopens for fresh creates on a local worker."""
        lost = 0
        with self._lock:
            info = self._promoting.get(shard)
            if info is None or not info.get("fed"):
                return
            del self._promoting[shard]
            for sid in [
                s for s, e in self.sessions.items() if e.shard == shard
            ]:
                e = self.sessions.pop(sid)
                self._cells -= e.height * e.width
                self._m_sessions_lost.inc()
                lost += 1
            if info.get("span") is not None:
                info["span"].set(outcome="lost", sessions=lost).finish()
            self._rebuild_routes_locked()
            self._wake.set()
        if self.events is not None:
            self.events.emit(
                "serve_federation_promotion_expired", shard=shard, lost=lost,
            )

    def on_shard_home(self, member_name: str, msg: dict) -> None:
        """A worker re-homed its control channel here after its frontend
        died (``SHARD_HOME``): its session list IS the truth.  Install or
        refresh index rows from it, point their shards at the worker,
        close the federation failover windows they held (digest-certified
        resume: the worker's epoch/digest replace the placeholder's
        replicated floor), and let replication appoint fresh replicas."""
        rows = [
            r for r in (msg.get("sessions") or [])
            if isinstance(r, dict) and r.get("id")
        ]
        now = time.monotonic()
        touched: set = set()
        closed = 0
        with self._lock:
            for row in rows:
                sid = str(row["id"])
                shard = shard_of(sid, self.n_shards)
                e = self.sessions.get(sid)
                if e is None:
                    e = _Entry(
                        sid, str(row.get("tenant", "default")), "batch",
                        str(row.get("rule", "B3/S23")),
                        int(row.get("height", 0)), int(row.get("width", 0)),
                        int(row.get("seed", 0)),
                        float(row.get("density", 0.5)), shard,
                    )
                    self.sessions[sid] = e
                    self._cells += e.height * e.width
                e.epoch = int(row.get("epoch", e.epoch))
                if row.get("digest") is not None:
                    e.digest = row["digest"]
                e.repl_epoch = -1
                e.repl_dirty_since = now
                e.last_used = now
                self.shard_owner[shard] = member_name
                touched.add(shard)
            for shard in touched:
                info = self._promoting.get(shard)
                if info is not None and info.get("fed"):
                    del self._promoting[shard]
                    if info.get("span") is not None:
                        info["span"].set(outcome="rehomed").finish()
                    closed += 1
            self._rebuild_routes_locked()
            if self._replicate:
                self._refresh_replicas_locked()
            self._wake.set()
        if self.events is not None:
            self.events.emit(
                "serve_shard_home", worker=member_name, sessions=len(rows),
                shards=len(touched), windows_closed=closed,
            )

    def _update_lag_locked(self, now: float) -> set:
        """Per-shard replication lag gauges (age of the oldest un-acked
        update; defined only while a replica exists — single-copy shards
        surface through the single-copy gauge instead) and the over-bound
        alert set.  Returns shards NEWLY over the bound so the caller can
        emit events outside the lock."""
        lag: Dict[int, float] = {}
        if self._replicate:
            for e in self.sessions.values():
                if (
                    e.shard is None
                    or e.repl_dirty_since is None
                    or self.shard_replica.get(e.shard) is None
                ):
                    continue
                lag[e.shard] = max(
                    lag.get(e.shard, 0.0), now - e.repl_dirty_since
                )
        for shard in self._lag_minted - set(lag):
            # Reclaim, the breaker-reset hygiene discipline: a caught-up
            # (or emptied, or lost) shard's series reads 0, not its last
            # stale lag.
            self._m_repl_lag.labels(shard=str(shard)).set(0.0)
        for shard, val in lag.items():
            self._m_repl_lag.labels(shard=str(shard)).set(val)
        self._lag_minted |= set(lag)
        alert = {s for s, v in lag.items() if v > self.repl_max_lag_s}
        fresh = alert - self._lag_alert
        self._lag_alert = alert
        self._lag_snapshot = lag
        return fresh

    # -- tiled (mega-board) sessions ------------------------------------------

    def _pick_worker_locked(self) -> Optional[str]:
        members = self.membership.placeable_members() or (
            self.membership.alive_members()
        )
        if not members:
            return None
        names = sorted(m.name for m in members)
        return names[next(self._rr) % len(names)]

    def _step_tiled(self, sid: str, entry: _Entry, steps: int) -> Tuple[int, int]:
        if steps > self.max_steps:
            # No fast-forward lane for tiled sessions (their rules are the
            # general totalistic family); the fairness bound stands.
            self._reject(
                "max_steps",
                f"steps {steps} over serve_max_steps={self.max_steps} "
                f"for a tiled session; chunk the request",
            )
        with self._lock:
            t = self.tiled.get(sid)
        if t is None:
            raise KeyError(sid)
        if t.mode == "resident":
            return self._step_tiled_resident(sid, entry, t, steps)
        with t.steplock:
            board = t.board
            H, W = board.shape
            remaining = steps
            lanes_parts: List = []
            while remaining > 0:
                k = min(remaining, self.tile_chunk)
                pends: List[_Pending] = []
                round_bytes = 0
                for gy, gx, th, tw in t.tiles:
                    rows = np.arange(gy - k, gy + th + k) % H
                    cols = np.arange(gx - k, gx + tw + k) % W
                    padded = np.ascontiguousarray(board[np.ix_(rows, cols)])
                    with self._lock:
                        member = self._pick_worker_locked()
                    if member is None:
                        self._reject(
                            "no_workers",
                            "no serve workers available for tile chunks",
                        )
                    state = pack_tile(padded)
                    round_bytes += int(getattr(state["data"], "nbytes", 0))
                    pends.append(self._submit(
                        {
                            "op": "step_raw", "rid": 0, "rule": entry.rule_s,
                            "k": int(k), "state": state,
                            "origin": [int(gy), int(gx)], "width": int(W),
                            "interior": [int(k), int(k + th), int(k),
                                         int(k + tw)],
                        },
                        sid=sid, kind="tile", member=member,
                    ))
                # ALL chunk results land before ANY tile scatters: a
                # failure mid-chunk (worker losses exhausting the retry
                # budget) must leave the board wholly at its pre-chunk
                # epoch — a half-scattered board would mix epochs and
                # serve silently corrupt state with a fresh digest.
                results = [self._await_tile(p) for p in pends]
                lanes_parts = []
                for result, (gy, gx, th, tw) in zip(results, t.tiles):
                    board[gy:gy + th, gx:gx + tw] = unpack_tile(
                        result["state"]
                    )
                    round_bytes += int(getattr(
                        result["state"]["data"], "nbytes", 0
                    ))
                    lanes_parts.append(
                        [int(result["digest"][0]), int(result["digest"][1])]
                    )
                self._m_tiled_bytes.observe(round_bytes)
                remaining -= k
                t.epoch += k
                # Per ROUND, not after the loop: a later round's failure
                # leaves the board legitimately advanced to THIS round's
                # epoch, and the stored lanes must describe that state —
                # a stale digest on the certification surface is worse
                # than a partial step.
                t.lanes = odigest.merge_lanes(lanes_parts)
            epoch, digest = t.epoch, odigest.value(t.lanes)
        with self._lock:
            if self.sessions.get(sid) is entry:
                entry.epoch = epoch
                entry.digest = odigest.format_digest(digest)
        return epoch, digest

    def _await_tile(self, p: _Pending) -> dict:
        """Wait one pure tile chunk out; a worker loss just replays it on
        another worker (the op is a function of its operands — nothing to
        lose)."""
        last: Optional[BaseException] = None
        for _ in range(TILE_OP_RETRIES):
            try:
                return self._await(p)
            except (TimeoutError, RuntimeError) as e:
                last = e
                with self._lock:
                    member = self._pick_worker_locked()
                if member is None:
                    break
                op = dict(p.op)
                p = self._submit(op, sid=p.sid, kind="tile", member=member)
        raise last if last is not None else RuntimeError("tile chunk failed")

    # -- worker-resident tiled sessions ---------------------------------------

    @staticmethod
    def _ckey(c: Tuple[int, int]) -> str:
        return f"{c[0]},{c[1]}"

    def _install_tiled(self, sid: str, entry: _Entry,
                       board: np.ndarray) -> _ResidentTiled:
        """Create-time installation: place each chunk on a worker (round-
        robin over the placeable set), appoint replicas, and ship every
        chunk ONCE — the last time its full state crosses the frontend
        until a render asks for it."""
        t = _ResidentTiled(
            sid, entry.rule_s, board, self.tile_side, self.tile_chunk
        )
        with self._lock:
            members = self.membership.placeable_members() or (
                self.membership.alive_members()
            )
            if not members:
                self._reject(
                    "no_workers", "no serve workers for a tiled session"
                )
            names = sorted(m.name for m in members)
            for i, c in enumerate(sorted(t.tiles)):
                t.owner[c] = names[i % len(names)]
            self._assign_tiled_replicas_locked(t)
        pends = []
        for c, (gy, gx, th, tw) in sorted(t.tiles.items()):
            pends.append(self._submit(
                {
                    "op": "tiled_install", "rid": 0, "sid": sid,
                    "rule": t.rule_s, "H": t.H, "W": t.W,
                    "grid": [t.ny, t.nx], "chunk": list(c),
                    "origin": [gy, gx], "shape": [th, tw], "k": t.k,
                    "state": pack_tile(board[gy:gy + th, gx:gx + tw]),
                    "epoch": 0,
                    "replicate": self._replicate,
                },
                sid=sid, kind="tile_ctl", member=t.owner[c],
            ))
        try:
            for p in pends:
                self._await(p)
        except BaseException:
            with self._lock:
                self._drop_tiled_locked(sid, t)
            raise
        return t

    def _tiled_owner_wire_locked(self, t: _ResidentTiled) -> Dict[str, list]:
        """chunk key -> [owner name, peer host, peer port] for one round's
        halo aiming (caller holds the lock)."""
        out: Dict[str, list] = {}
        for c, owner in t.owner.items():
            m = self.membership.get(owner)
            if m is None or not m.alive:
                raise AdmissionError(
                    "no_workers", f"tiled chunk owner {owner} is gone"
                )
            out[self._ckey(c)] = [owner, m.peer_host, int(m.peer_port)]
        return out

    def _step_tiled_resident(
        self, sid: str, entry: _Entry, t: _ResidentTiled, steps: int
    ) -> Tuple[int, int]:
        """The steady-state tentpole: per round, ONE light op per worker
        (epoch barrier + halo aiming map), O(perimeter) peer strips on the
        workers' own wire, digest lanes only at barrier/final rounds —
        the frontend never touches cell state."""
        with t.steplock:
            with self._lock:
                if t.promoting:
                    self._reject(
                        "failover",
                        f"tiled session {sid} is mid-promotion; retry",
                        link=self._tiled_link_locked(sid),
                    )
                owners_wire = self._tiled_owner_wire_locked(t)
                floor = t.certified()
                by_member: Dict[str, List[list]] = {}
                for c, owner in t.owner.items():
                    by_member.setdefault(owner, []).append(list(c))
            # ONE op per worker for the WHOLE request: the per-round step
            # counts and the absolute snapshot epochs ride along, and the
            # workers chain the intermediate rounds peer-to-peer — the
            # frontend re-enters only at the request barrier.
            ks: List[int] = []
            snap_epochs: List[int] = []
            e = t.epoch
            remaining = steps
            while remaining > 0:
                k = min(remaining, t.k)
                ks.append(int(k))
                e += k
                remaining -= k
                t.round_idx += 1
                if (
                    self._replicate
                    and t.round_idx % self.tiled_snap_rounds == 0
                ):
                    snap_epochs.append(int(e))
            pends = [
                self._submit(
                    {
                        "op": "tiled_step", "rid": 0, "sid": sid,
                        "epoch": t.epoch, "ks": ks, "chunks": chunks,
                        "owners": owners_wire, "digest": True,
                        "snap_epochs": snap_epochs, "floor": floor,
                    },
                    sid=sid, kind="tile_ctl", member=member,
                )
                for member, chunks in sorted(by_member.items())
            ]
            try:
                results = [self._await(p, grace=True) for p in pends]
            except BaseException as e:
                with self._lock:
                    promoting = t.promoting
                    link = self._tiled_link_locked(sid)
                if promoting:
                    self._reject(
                        "failover",
                        f"tiled session {sid} lost a worker mid-step; "
                        f"it resumes at its last certified epoch — retry",
                        link=link,
                    )
                # A request that failed WITHOUT a worker loss (one op
                # timing out on a slow worker, a halo batch exhausting
                # its retries) may have advanced SOME workers' chunks:
                # the frontend epoch and the worker epochs must never
                # drift apart silently, or every later request errors
                # forever.  Resync the whole session to its certified
                # snapshot — the same consistent-rollback machinery a
                # promotion uses, with no chunks to promote.
                self._begin_tiled_resync(sid, t)
                with self._lock:
                    link = self._tiled_link_locked(sid)
                self._reject(
                    "failover",
                    f"tiled session {sid} step failed mid-request "
                    f"({e!r}); the session resyncs to its last "
                    f"certified epoch — retry",
                    link=link,
                )
            request_bytes = sum(
                int(r.get("halo_bytes", 0)) for r in results
            )
            for _ in ks:
                self._m_tiled_bytes.observe(request_bytes / len(ks))
            t.epoch += steps
            lanes_parts: List[list] = []
            pop = 0
            for r in results:
                lanes_parts.extend(r.get("lanes", {}).values())
                pop += sum(int(v) for v in r.get("pop", {}).values())
            t.lanes = odigest.merge_lanes(lanes_parts)
            t.population = pop
            epoch, digest = t.epoch, odigest.value(t.lanes)
        with self._lock:
            if self.sessions.get(sid) is entry:
                entry.epoch = epoch
                entry.digest = odigest.format_digest(digest)
        return epoch, digest

    def _fetch_tiled_board(self, sid: str, t: _ResidentTiled) -> np.ndarray:
        """Render pull (GET ?with_board=1 only): gather the resident
        chunks and assemble the full board — the one remaining O(area)
        path, paid exactly when a tenant asks to SEE the board."""
        with self._lock:
            by_member: Dict[str, List[list]] = {}
            for c, owner in t.owner.items():
                by_member.setdefault(owner, []).append(list(c))
        pends = [
            self._submit(
                {"op": "tiled_fetch", "rid": 0, "sid": sid, "chunks": chunks},
                sid=sid, kind="tile_ctl", member=member,
            )
            for member, chunks in sorted(by_member.items())
        ]
        board = np.zeros((t.H, t.W), dtype=np.uint8)
        try:
            for p in pends:
                res = self._await(p)
                for row in res["states"]:
                    if int(row["epoch"]) != t.epoch:
                        # Never serve a torn board: a chunk off the
                        # session epoch means a failed request left the
                        # workers desynchronized (the resync path owns
                        # recovery; this render answers retryably).
                        raise RuntimeError(
                            f"tiled chunk {row['chunk']} at epoch "
                            f"{row['epoch']}, session at {t.epoch}"
                        )
                    gy, gx = (int(v) for v in row["origin"])
                    th, tw = (int(v) for v in row["shape"])
                    board[gy:gy + th, gx:gx + tw] = unpack_tile(row["state"])
        except BaseException:
            with self._lock:
                promoting = t.promoting
                link = self._tiled_link_locked(sid)
            if promoting:
                self._reject(
                    "failover",
                    f"tiled session {sid} is mid-promotion; retry",
                    link=link,
                )
            raise
        return board

    def _drop_tiled_locked(self, sid: str, t: _ResidentTiled) -> None:
        """Release a resident session's worker-side state (delete, evict,
        honest loss, failed install) — best-effort ops to every live
        owner and replica (caller holds the lock)."""
        for name in {
            n for n in list(t.owner.values()) + list(t.replica.values())
            if n is not None
        }:
            m = self.membership.get(name)
            if m is None or not m.alive:
                continue
            op = (
                {"op": "tiled_drop", "rid": 0, "sid": sid}
                if name in t.owner.values()
                else {"op": "tiled_replica_drop", "rid": 0, "sid": sid}
            )
            try:
                self._submit(op, sid=sid, kind="tile_ctl", member=name,
                             on_done=lambda _p: None)
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass

    # -- resident tiled: replication relay ------------------------------------

    def _tiled_replica_for_locked(
        self, sid: str, c: Tuple[int, int], owner: Optional[str],
        names: List[str], current: Optional[str],
    ) -> Optional[str]:
        """Sticky-first, rendezvous-second, never the chunk's owner —
        the shard-replica policy at chunk granularity."""
        if not self._replicate or owner is None:
            return None
        if current is not None and current != owner and current in names:
            return current
        return rendezvous_pick(
            f"{sid}:{c[0]},{c[1]}", (n for n in names if n != owner)
        )

    def _assign_tiled_replicas_locked(self, t: _ResidentTiled) -> None:
        """Reconcile one resident session's replica map with the current
        membership (caller holds the lock): re-homed chunks reset their
        watermark and tell the primary to restart its stream; a session
        with no possible replica parks its primaries' streams."""
        names = sorted(
            m.name for m in self.membership.placeable_members()
        )
        resets: Dict[str, List[str]] = {}
        drops: List[Tuple[str, list]] = []
        for c, owner in t.owner.items():
            desired = self._tiled_replica_for_locked(
                t.sid, c, owner, names, t.replica.get(c)
            )
            cur = t.replica.get(c)
            if desired == cur:
                continue
            t.replica[c] = desired
            t.acked[c] = -1
            if cur is not None:
                m = self.membership.get(cur)
                if m is not None and m.alive:
                    drops.append((cur, list(c)))
            if owner is not None:
                resets.setdefault(owner, []).append(self._ckey(c))
        was_parked = t.parked
        t.parked = self._replicate and all(
            r is None for r in t.replica.values()
        )
        for cur, chunk in drops:
            try:
                self._submit(
                    {"op": "tiled_replica_drop", "rid": 0, "sid": t.sid,
                     "chunks": [chunk]},
                    kind="tile_ctl", member=cur, on_done=lambda _p: None,
                )
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        if t.parked and not was_parked:
            for owner in set(t.owner.values()):
                m = self.membership.get(owner)
                if m is not None and m.alive:
                    self._enqueue_ctrl_locked(owner, {
                        "type": P.SHARD_REPLICATE_ACK, "shard": -1,
                        "tiled_parked": [t.sid],
                    })
            return
        for owner, keys in resets.items():
            m = self.membership.get(owner)
            if m is not None and m.alive:
                self._enqueue_ctrl_locked(owner, {
                    "type": P.SHARD_REPLICATE_ACK, "shard": -1,
                    "tiled_reset": {t.sid: keys},
                })

    def on_tiled_replicate(self, member_name: str, payloads: list) -> None:
        """A primary's resident-chunk snapshot stream: relay each payload
        to its chunk's replica through the replica's op FIFO; acks flow
        back to the primary with the per-chunk watermark and the
        session's certified floor."""
        by_replica: Dict[Tuple[str, str], List[dict]] = {}
        with self._lock:
            if self._stopped:
                return
            for pay in payloads:
                sid = str(pay.get("sid"))
                t = self.tiled.get(sid)
                if (
                    t is None or t.mode != "resident" or t.promoting
                    or sid in self._tiled_promoting
                ):
                    continue
                c = tuple(int(v) for v in pay["chunk"])
                if t.owner.get(c) != member_name:
                    continue  # stale stream from a former owner
                repl = t.replica.get(c)
                m = self.membership.get(repl) if repl is not None else None
                if m is None or not m.alive:
                    continue  # parked / re-homing; the refresh pass acks
                by_replica.setdefault((sid, repl), []).append(pay)
                self._m_repl_bytes.inc(
                    int(getattr(pay.get("state", {}).get("data"), "nbytes", 0))
                )
            for (sid, repl), chunk_pays in by_replica.items():
                t = self.tiled.get(sid)
                self._submit(
                    {"op": "tiled_replicate", "rid": 0, "sid": sid,
                     "chunks": chunk_pays, "floor": t.certified()},
                    kind="replicate", member=repl,
                    on_done=lambda p, sid=sid, primary=member_name: (
                        self._on_tiled_replicated(sid, primary, p)
                    ),
                )

    def _on_tiled_replicated(self, sid: str, primary: str,
                             p: _Pending) -> None:
        """A replica acked resident-chunk snapshots: advance per-chunk
        watermarks and relay the ack (plus the new certified floor) to
        the primary's op FIFO.  A failed install is simply not acked —
        the primary's next pass retransmits."""
        if p.error is not None or not p.result:
            return
        acked = dict(p.result.get("acked", {}))
        if not acked:
            return
        with self._lock:
            t = self.tiled.get(sid)
            if t is None or t.mode != "resident":
                return
            wire_acked: Dict[str, int] = {}
            for ck, epoch in acked.items():
                c = tuple(int(v) for v in ck.split(","))
                if t.replica.get(c) != p.member:
                    continue  # re-homed since: stale ack
                if int(epoch) > t.acked.get(c, -1):
                    t.acked[c] = int(epoch)
                wire_acked[ck] = t.acked[c]
            if not wire_acked:
                return
            pm = self.membership.get(primary)
            if pm is not None and pm.alive:
                self._enqueue_ctrl_locked(primary, {
                    "type": P.SHARD_REPLICATE_ACK, "shard": -1,
                    "tiled_acked": {sid: wire_acked},
                    "tiled_floor": {sid: t.certified()},
                })

    # -- resident tiled: promotion on worker loss ------------------------------

    def _begin_tiled_resync(self, sid: str, t: _ResidentTiled) -> None:
        """A step request failed without a member loss (timeout on a slow
        worker, halo retry exhaustion): some workers' chunks may have
        advanced past the frontend's epoch.  Roll the WHOLE session back
        to its certified snapshot — promotion with zero lost chunks —
        so frontend and workers agree again; no certified state = honest
        loss (the session could otherwise serve torn state forever).
        Caller holds the steplock (the failed request's own hold)."""
        with self._lock:
            if t.promoting or self.tiled.get(sid) is not t:
                return
            C = t.certified() if self._replicate else -1
            if C < 0:
                e = self.sessions.pop(sid, None)
                self.tiled.pop(sid, None)
                if e is not None:
                    self._cells -= e.height * e.width
                self._m_sessions_lost.inc()
                self._m_tiled.set(len(self.tiled))
                self._drop_tiled_locked(sid, t)
                return
            t.promoting = True
            survivors = sorted(set(t.owner.values()))
            info = {
                "t0": time.monotonic(),
                "span": self.tracer.start(
                    "serve.promote", node="frontend", sid=sid,
                    kind="tiled_resync", epoch=C,
                ),
            }
            self._tiled_promoting[sid] = info
        # A resync means a request failed in a way that may have torn the
        # session's epoch consensus — exactly the moment a post-mortem
        # wants the ring buffers (the promotion path dumps separately;
        # this reason marks the no-member-loss variant).
        flight = getattr(self.tracer, "flight", None)
        if flight is not None:
            flight.dump("serve_resync", node="frontend")
        self._launch_tiled_promotion(
            (sid, t, C, {}, survivors, info), lost_member=""
        )

    def _begin_tiled_promotions_locked(self, name: str) -> List[tuple]:
        """Worker ``name`` died.  For every resident session touched:
        chunks it OWNED promote from their replicas at the session's
        certified epoch (survivor chunks roll back to it — the whole
        session resumes consistent); chunks it replicated re-home.
        Sessions with no certified resume point are lost honestly.
        Returns promotion plans for _launch_tiled_promotion (caller holds
        the lock)."""
        plans: List[tuple] = []
        for sid, t in list(self.tiled.items()):
            if t.mode != "resident":
                continue
            lost = [c for c, o in t.owner.items() if o == name]
            for c, r in list(t.replica.items()):
                if r == name:
                    # The dead member was a REPLICA here: the standby
                    # state died with it; the refresh pass re-homes.
                    t.replica[c] = None
                    t.acked[c] = -1
            if not lost:
                continue
            C = t.certified(lost) if self._replicate else -1
            live_repl = all(
                t.replica.get(c) is not None
                and (m := self.membership.get(t.replica[c])) is not None
                and m.alive
                for c in lost
            )
            if t.promoting or C < 0 or not live_repl:
                # Honest loss: no certified resume point (or a double
                # failure mid-promotion).
                e = self.sessions.pop(sid, None)
                self.tiled.pop(sid, None)
                if e is not None:
                    self._cells -= e.height * e.width
                self._m_sessions_lost.inc()
                self._m_tiled.set(len(self.tiled))
                self._drop_tiled_locked(sid, t)
                continue
            t.promoting = True
            lost_set = set(lost)
            # Every member still owning a SURVIVING chunk rolls it back
            # to C (rollback first on each FIFO, so a member that both
            # survives and promotes orders correctly).
            survivors = sorted({
                o for c, o in t.owner.items()
                if c not in lost_set and o != name
            })
            by_replica: Dict[str, List[list]] = {}
            for c in lost:
                by_replica.setdefault(t.replica[c], []).append(list(c))
                t.owner[c] = t.replica[c]
                t.replica[c] = None
                t.acked[c] = -1
            info = {
                "t0": time.monotonic(),
                "span": self.tracer.start(
                    "serve.promote", node="frontend", sid=sid,
                    kind="tiled", chunks=len(lost), epoch=C,
                ),
            }
            self._tiled_promoting[sid] = info
            plans.append((sid, t, C, by_replica, survivors, info))
        return plans

    def _launch_tiled_promotion(self, plan: tuple, lost_member: str) -> None:
        """Fire one resident-session promotion on its own thread (the
        caller is a frontend reader/maintenance thread and must not block
        on worker round-trips)."""
        threading.Thread(
            target=self._run_tiled_promotion, args=(plan, lost_member),
            daemon=True, name=f"tiled-promote-{plan[0]}",
        ).start()

    def _run_tiled_promotion(self, plan: tuple, lost_member: str) -> None:
        sid, t, C, by_replica, survivors, info = plan
        flight = getattr(self.tracer, "flight", None)
        if flight is not None:
            flight.dump("serve_promote", node="frontend")
        if self.events is not None:
            self.events.emit(
                "serve_promotion_started", sid=sid, kind="tiled",
                lost=lost_member, epoch=C,
                chunks=sum(len(v) for v in by_replica.values()),
            )
        lanes_parts: List[list] = []
        pop = 0
        ok = True
        try:
            # Survivors FIRST: the rollback cancels any round stalled on
            # halos from the dead worker, so an in-flight step fails fast
            # (its caller answers 429 failover) instead of waiting out
            # the barrier timeout.
            pends = [
                self._submit(
                    {"op": "tiled_rollback", "rid": 0, "sid": sid,
                     "epoch": int(C)},
                    sid=sid, kind="tile_ctl", member=m,
                )
                for m in survivors
            ]
            pends += [
                self._submit(
                    {"op": "tiled_promote", "rid": 0, "sid": sid,
                     "epoch": int(C), "chunks": chunks, "meta": t.meta()},
                    sid=sid, kind="tile_ctl", member=m,
                )
                for m, chunks in sorted(by_replica.items())
            ]
            for p in pends:
                res = self._await(p)
                rows = res.get("restored", []) + res.get("installed", [])
                if res.get("missing") or res.get("failed"):
                    ok = False
                for row in rows:
                    lanes_parts.append([int(v) for v in row["digest"]])
                    pop += int(row.get("pop", 0))
            if len(lanes_parts) != len(t.tiles):
                ok = False
        except BaseException:  # noqa: BLE001 — resolved below, honestly
            ok = False
        with self._lock:
            self._tiled_promoting.pop(sid, None)
            entry = self.sessions.get(sid)
            if not ok or entry is None:
                t.promoting = False
                if entry is not None:
                    del self.sessions[sid]
                    self._cells -= entry.height * entry.width
                    self._m_sessions_lost.inc()
                self.tiled.pop(sid, None)
                self._m_tiled.set(len(self.tiled))
                self._drop_tiled_locked(sid, t)
                if info["span"] is not None:
                    info["span"].set(outcome="lost").finish()
            else:
                t.epoch = int(C)
                t.lanes = odigest.merge_lanes(lanes_parts)
                t.population = pop
                t.round_idx = 0
                entry.epoch = int(C)
                entry.digest = odigest.format_digest(
                    odigest.value(t.lanes)
                )
                t.promoting = False
                self._assign_tiled_replicas_locked(t)
                self._m_promotions.inc()
                if info["span"] is not None:
                    info["span"].set(
                        outcome="promoted", epoch=int(C),
                        latency_s=round(
                            time.monotonic() - info["t0"], 6
                        ),
                    ).finish()
        if self.events is not None:
            self.events.emit(
                "serve_promotion_finished", sid=sid, kind="tiled",
                outcome="promoted" if ok else "lost", epoch=int(C),
            )

    # -- resident tiled: chunk migration (drain / load rebalancing) -----------

    def _plan_tiled_moves_locked(self, now: float,
                                 drain_only: bool) -> List[tuple]:
        """Ask the chunk-plane Rebalancer for (key, source, dest) moves
        over every resident, non-promoting session (caller holds the
        lock)."""
        owners: Dict[tuple, str] = {}
        replicas: Dict[tuple, Optional[str]] = {}
        for sid, t in self.tiled.items():
            if t.mode != "resident" or t.promoting:
                continue
            for c, o in t.owner.items():
                owners[(sid, c)] = o
                replicas[(sid, c)] = t.replica.get(c)
        if not owners:
            return []
        return self.tiled_rebalancer.plan_resident(
            owners, self.membership.alive_members(), now,
            drain_only=drain_only, replicas=replicas,
        )

    def _migrate_tiled_chunk(self, key: tuple, source: str, dest: str,
                             seq: int) -> None:
        """Move one resident chunk, digest-certified, under the session's
        steplock — a move can never interleave with an epoch barrier, so
        a torn halo is unrepresentable (the next round's op simply aims
        at the new owner)."""
        sid, c = key
        with self._lock:
            t = self.tiled.get(sid)
        aborted = "setup"
        if t is not None and t.steplock.acquire(
            timeout=self.tiled_rebalancer.deadline_s
        ):
            try:
                aborted = self._migrate_tiled_chunk_held(
                    t, key, source, dest, seq
                )
            except BaseException as e:  # noqa: BLE001 — the in-flight
                # record MUST resolve (abort), whatever broke
                aborted = repr(e)
            finally:
                t.steplock.release()
        now = time.monotonic()
        with self._lock:
            if aborted is None:
                self.tiled_rebalancer.complete(key)
                self._m_chunk_migrations.inc()
            else:
                self.tiled_rebalancer.abort(key, now)
        if self.events is not None:
            if aborted is None:
                self.events.emit(
                    "serve_tiled_chunk_migrated", sid=sid,
                    chunk=list(c), source=source, dest=dest,
                )
            else:
                self.events.emit(
                    "serve_tiled_chunk_migration_aborted", sid=sid,
                    chunk=list(c), source=source, dest=dest,
                    reason=aborted,
                )

    def _migrate_tiled_chunk_held(self, t, key, source, dest,
                                  seq) -> Optional[str]:
        """The move body (steplock held).  Returns None on commit, else
        the abort reason."""
        sid, c = key
        with self._lock:
            if (
                t.promoting
                or self.tiled.get(sid) is not t
                or t.owner.get(c) != source
            ):
                return "stale"
            dm = self.membership.get(dest)
            if dm is None or not dm.alive:
                return "dest_lost"
        try:
            p = self._submit(
                {"op": "tiled_export", "rid": 0, "sid": sid,
                 "chunks": [list(c)]},
                sid=sid, kind="tile_ctl", member=source,
            )
            pay = self._await(p)["chunks"][0]
        except BaseException as e:  # noqa: BLE001 — abort, never raise
            return f"export: {e!r}"
        lanes = odigest.digest_payload_np(
            pay["state"], tuple(int(v) for v in pay["origin"]),
            int(pay["width"]),
        )
        self._m_digest_checks.inc()
        if [int(lanes[0]), int(lanes[1])] != [int(v) for v in pay["digest"]]:
            self._m_digest_mismatches.inc()
            if self.events is not None:
                self.events.emit(
                    "serve_tiled_digest_mismatch", sid=sid,
                    chunk=list(c), source=source,
                )
            return "digest_mismatch"
        try:
            p = self._submit(
                {"op": "tiled_adopt", "rid": 0, "sid": sid,
                 "meta": t.meta(), "chunks": [pay]},
                sid=sid, kind="tile_ctl", member=dest,
            )
            self._await(p)
        except BaseException as e:  # noqa: BLE001 — abort, never raise
            return f"adopt: {e!r}"
        with self._lock:
            if self.tiled.get(sid) is not t or t.promoting:
                return "stale"
            t.owner[c] = dest
            t.acked[c] = -1
            self._assign_tiled_replicas_locked(t)
            sm = self.membership.get(source)
            src_alive = sm is not None and sm.alive
        if src_alive:
            try:
                self._submit(
                    {"op": "tiled_chunk_drop", "rid": 0, "sid": sid,
                     "chunks": [list(c)]},
                    sid=sid, kind="tile_ctl", member=source,
                    on_done=lambda _p: None,
                )
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        return None

    # -- stats / health / lifecycle -------------------------------------------

    def _refresh_gauges_locked(self) -> None:
        shards: Dict[str, int] = {}
        for owner in self.shard_owner.values():
            if owner is not None:
                shards[owner] = shards.get(owner, 0) + 1
        sess: Dict[str, int] = {}
        for e in self.sessions.values():
            if e.shard is not None:
                owner = self.shard_owner.get(e.shard)
                if owner is not None:
                    sess[owner] = sess.get(owner, 0) + 1
        queues: Dict[str, int] = {}
        for p in self._pending.values():
            if p.member is not None:
                queues[p.member] = queues.get(p.member, 0) + 1
        for m in self.membership.alive_members():
            self._m_shards.labels(member=m.name).set(shards.get(m.name, 0))
            self._m_shard_sessions.labels(member=m.name).set(
                sess.get(m.name, 0)
            )
            self._m_wqueue.labels(member=m.name).set(queues.get(m.name, 0))
        self._health_snapshot = {
            "shards": shards, "sessions": sess, "queue_depths": queues,
        }

    def _refresh_gauges(self) -> None:
        with self._lock:
            self._refresh_gauges_locked()

    def health(self) -> dict:
        """The /healthz contribution: per-worker session-shard counts and
        queue depths (the migrations_inflight shape, serve edition)."""
        with self._lock:
            self._refresh_gauges_locked()
            snap = self._health_snapshot
            replicas: Dict[str, int] = {}
            single = 0
            for shard, owner in self.shard_owner.items():
                if owner is None:
                    continue
                r = self.shard_replica.get(shard)
                if r is not None:
                    replicas[r] = replicas.get(r, 0) + 1
                elif self._replicate and shard not in self._promoting:
                    single += 1
            chunks_by_worker: Dict[str, int] = {}
            for t in self.tiled.values():
                if t.mode == "resident":
                    for o in t.owner.values():
                        chunks_by_worker[o] = chunks_by_worker.get(o, 0) + 1
            return {
                "sessions": len(self.sessions),
                "cells": self._cells,
                "tiled_sessions": len(self.tiled),
                "tiled_resident": {
                    "enabled": self.tiled_resident,
                    "chunks_by_worker": chunks_by_worker,
                    "chunk_migrations_inflight": len(
                        self.tiled_rebalancer.inflight
                    ),
                    "promotions_inflight": len(self._tiled_promoting),
                },
                "shards_total": self.n_shards,
                "shards_by_worker": dict(snap["shards"]),
                "sessions_by_worker": dict(snap["sessions"]),
                "queue_depth_by_worker": dict(snap["queue_depths"]),
                "shard_migrations_inflight": len(self.rebalancer.inflight),
                "held_ops": sum(len(v) for v in self._held.values()),
                "draining": self._draining,
                "replication": {
                    "enabled": self._replicate,
                    "replicas_by_worker": replicas,
                    "single_copy_shards": (
                        single if self._replicate
                        else sum(
                            1 for o in self.shard_owner.values()
                            if o is not None
                        )
                    ),
                    "promotions_inflight": len(self._promoting),
                    "max_lag_s": round(
                        max(self._lag_snapshot.values(), default=0.0), 3
                    ),
                    "lag_alert_shards": sorted(self._lag_alert),
                },
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self.sessions),
                "cells": self._cells,
                "queue_depth": len(self._pending),
                "max_sessions": self.max_sessions,
                "max_cells": self.max_cells,
                "size_classes": list(self.size_classes),
                "shards": self.n_shards,
                "workers": len(self.membership.alive_members()),
            }

    def drain(self, timeout: float = 30.0) -> bool:
        """Refuse NEW work, run the in-flight ops dry — the plane half of
        a graceful shutdown (worker drains are the per-member story; this
        is whole-service SIGTERM)."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout  # graftlint: waive GL-HAZ04 -- real-time bound pairs with the real sleep pacing below; shutdown must stay bounded
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return True
            time.sleep(0.05)
        return False

    def close(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            doomed = list(self._pending.values())
            self._pending.clear()
            self._outq.clear()
            self._held.clear()
            # Promotion spans must not outlive the run (the elastic-plane
            # discipline): finish open ones with outcome=shutdown.
            for info in self._promoting.values():
                if info.get("span") is not None:
                    info["span"].set(outcome="shutdown").finish()
            self._promoting.clear()
            for info in self._tiled_promoting.values():
                if info.get("span") is not None:
                    info["span"].set(outcome="shutdown").finish()
            self._tiled_promoting.clear()
            self._wake.set()
        for p in doomed:
            self._resolve(p, error=RuntimeError("router is closed"))
        self._flusher.join(timeout=5)


def run_serve_cluster(config, *, min_backends: int = 1) -> int:
    """The ``serve --serve-cluster on`` role body: a serve-only cluster
    frontend — workers join like any cluster (``backend`` role), the
    tenant surface rides the obs endpoint, and SIGTERM drains."""
    from akka_game_of_life_tpu.runtime.frontend import Frontend
    from akka_game_of_life_tpu.runtime.signals import mask_interrupts

    fe = Frontend(config, min_backends=min_backends)
    canary = None
    fe.start()
    print(
        f"serve frontend listening on {config.host}:{fe.port} "
        f"({config.serve_shards} shards)",
        flush=True,
    )
    try:
        if not fe.wait_for_backends():
            print(
                f"error: only {len(fe.membership.alive_members())} of "
                f"{min_backends} backends joined within "
                f"{config.wait_for_backends_s}s",
                flush=True,
            )
            fe.stop()
            return 1
        port = fe._metrics_server.port if fe._metrics_server else None
        print(
            f"cluster serving /boards (+/metrics,/healthz,/trace,/slo) on "
            f":{port} — {fe.serve_plane.max_sessions} sessions / "
            f"{fe.serve_plane.max_cells} cells cluster-wide, "
            f"{len(fe.membership.alive_members())} worker(s)",
            flush=True,
        )
        if config.serve_canary and port:
            from akka_game_of_life_tpu.serve.canary import CanaryProber

            # Probes the REAL tenant surface (loopback HTTP), pinned one
            # session per worker via the plane's shard map.
            canary = CanaryProber(
                config, base=f"http://127.0.0.1:{port}",
                registry=fe.metrics, tracer=fe.tracer, events=fe.events,
                plane=fe.serve_plane,
            )
            canary.start()
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("serve: interrupted; draining", flush=True)
        if canary is not None:
            canary.close()
        drained = fe.serve_plane.drain()
        print(
            "serve: drained" if drained
            else "serve: drain timed out; aborting pending ops",
            flush=True,
        )
        with mask_interrupts():
            fe.stop()
        return 130
    fe.stop()
    return 0
