"""Digest-certified canary prober: black-box serve-plane health.

White-box metrics say what the plane *thinks* it is doing; the canary
says what a tenant actually *gets*.  A background synthetic tenant
(``tenant="canary"``) pins one small session per serving worker — on the
cluster plane, :meth:`ClusterServePlane.canary_targets` names one owned
shard per worker and the prober *mines* a session id whose crc32 shard
hash lands there (the PR 13 routing function is pure, so the aim is
exact) — then steps each pinned board at a fixed cadence through the
REAL HTTP surface: the same URL parsing, admission, routing, wire
framing, vmapped batch engine, and digest pipeline every tenant request
rides.

Every answer is **digest-certified**: the prober maintains a local
pure-numpy oracle (:func:`ops.npkernel.step_np`, the same oracle the
test suite trusts) for each pinned board and compares the served digest
at the served epoch against the oracle chain.  The chain is a dict keyed
by epoch, so a failover that legitimately rolls a session back to its
replicated epoch still certifies — only an answer that matches *no*
epoch's truth is corruption.

Failure modes become paged signals within ONE cadence:

- **silent corruption** (a worker serving wrong cells with a confident
  digest) → digest mismatch → ``gol_canary_failures_total`` +
  flight dump (``reason=canary_fail``) carrying the failing probe's
  trace id;
- **a wedged worker** (routes fine, never answers) → probe timeout →
  the same failure path, plus ``gol_canary_staleness_seconds`` growing
  past the cadence;
- **an honest loss** (404 after an unreplicated crash) → the prober
  re-pins a fresh session and keeps probing — loss is the serve plane's
  own loud metric, not a canary corruption.

A 429 (failover window, draining) is *retryable by contract* and counts
as a ``rejected`` probe, never a failure — the canary measures the
tenant contract, and the contract says retry.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional

import numpy as np

from akka_game_of_life_tpu.obs import get_registry
from akka_game_of_life_tpu.obs.tracing import get_tracer
from akka_game_of_life_tpu.ops import digest as odigest
from akka_game_of_life_tpu.ops.npkernel import step_np
from akka_game_of_life_tpu.serve.sessions import shard_of
from akka_game_of_life_tpu.utils.patterns import random_grid

TENANT = "canary"
# Fixed seed: every pinned board is the same reproducible orbit, so a
# post-mortem can replay the oracle chain from the access log alone.
SEED = 7
DENSITY = 0.5
RULE = "conway"
# Oracle-chain retention (epochs): far past any legitimate failover
# rollback window, bounded so a long-lived prober cannot grow unbounded.
CHAIN_KEEP = 4096
# Sid-mining bound: P(miss) per draw is (1 - 1/n_shards); even 256
# shards clears in ~1500 draws with probability ~1-1e-3, and mining is
# a one-time cost per (re-)pin.
MINE_LIMIT = 100_000


class _Pin:
    """One pinned canary session: its id, its oracle board, and the
    digest chain the served answers are certified against."""

    __slots__ = ("worker", "shard", "sid", "board", "epoch", "digests",
                 "last_ok")

    def __init__(self, worker: str, shard: Optional[int], sid: str,
                 board: np.ndarray, now: float):
        self.worker = worker
        self.shard = shard
        self.sid = sid
        self.board = board
        self.epoch = 0
        self.digests: Dict[int, str] = {
            0: odigest.format_digest(odigest.value(
                odigest.digest_dense_np(board)
            ))
        }
        self.last_ok = now

    def expect(self, epoch: int) -> Optional[str]:
        """The oracle digest at ``epoch`` — stepping the local board
        forward as needed (None: the epoch fell off the kept chain)."""
        while self.epoch < epoch:
            self.board = step_np(self.board, RULE)
            self.epoch += 1
            self.digests[self.epoch] = odigest.format_digest(
                odigest.value(odigest.digest_dense_np(self.board))
            )
            stale = self.epoch - CHAIN_KEEP
            if stale in self.digests:
                del self.digests[stale]
        return self.digests.get(epoch)


class CanaryProber:
    """Background prober against a serve endpoint's real HTTP surface.

    ``plane`` (the cluster frontend's :class:`ClusterServePlane`) turns
    on per-worker pinning; without it one local session covers the
    single-process serve role.  ``probe_now()`` runs one full round
    synchronously — the unit the background thread repeats at
    ``serve_canary_interval_s``, and the handle tests drive directly.
    """

    def __init__(self, config, *, base: str, registry=None, tracer=None,
                 events=None, plane=None):
        self.base = base.rstrip("/")
        self.interval = float(getattr(config, "serve_canary_interval_s", 2.0))
        self.side = int(getattr(config, "serve_canary_side", 32))
        # Generous floor: a first-compile step legitimately takes seconds,
        # and a slow-but-correct answer must not page as a failure — a
        # truly wedged worker still pages via the staleness gauge within
        # one cadence, then via timeout failures past the floor.
        self.timeout = max(5.0, 2.0 * self.interval)
        self.plane = plane
        self.events = events
        self.tracer = tracer if tracer is not None else get_tracer()
        registry = registry if registry is not None else get_registry()
        self._m_probes = registry.counter(
            "gol_canary_probes_total", labelnames=("outcome",)
        )
        self._m_failures = registry.counter("gol_canary_failures_total")
        self._m_latency = registry.histogram("gol_canary_latency_seconds")
        self._m_staleness = registry.gauge("gol_canary_staleness_seconds")
        self._m_sessions = registry.gauge("gol_canary_sessions")
        self._pins: Dict[str, _Pin] = {}  # worker -> pin
        self._no_pin_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-canary"
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.timeout + 1.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.probe_now()
            except Exception:  # noqa: BLE001 — the prober must outlive any single bad round
                pass

    # -- one round ------------------------------------------------------------

    def probe_now(self) -> Dict[str, str]:
        """Pin any missing sessions, probe every pin once; returns
        worker -> outcome (the test surface)."""
        outcomes: Dict[str, str] = {}
        targets = self._targets()
        for worker, shard in targets.items():
            pin = self._pins.get(worker)
            if pin is None or pin.shard != shard:
                pin = self._pin(worker, shard)
                if pin is None:
                    # Couldn't (re-)seed this round: a transient refusal
                    # (draining, failover) or a birth mismatch (already
                    # counted as a failure by _pin).  Staleness keeps
                    # growing either way — a persistent inability to pin
                    # pages through that gauge, not a false corruption.
                    outcomes[worker] = "pin_failed"
                    self._m_probes.labels(outcome="pin_failed").inc()
                    continue
                self._pins[worker] = pin
            outcomes[worker] = self._probe(pin)
        # Stale pins for departed workers: drop (their sessions died or
        # migrated; coverage follows the live target set).
        for worker in [w for w in self._pins if w not in outcomes]:
            del self._pins[worker]
        self._m_sessions.set(len(self._pins))
        now = time.monotonic()
        if targets and not self._pins:
            # Nothing pinnable at all (surface down / every create
            # refused): the staleness clock must still run, or a dead
            # plane would read perfectly fresh.
            if self._no_pin_since is None:
                self._no_pin_since = now
            self._m_staleness.set(now - self._no_pin_since)
        else:
            self._no_pin_since = None
            self._m_staleness.set(max(
                (now - p.last_ok for p in self._pins.values()), default=0.0
            ))
        return outcomes

    def _targets(self) -> Dict[str, Optional[int]]:
        if self.plane is None:
            return {"local": None}
        try:
            return dict(self.plane.canary_targets())
        except Exception:  # noqa: BLE001 — a draining plane has no targets this round
            return {}

    def _mine_sid(self, worker: str, shard: Optional[int]) -> Optional[str]:
        if shard is None:
            return f"canary-{worker}-0"
        n = int(self.plane.n_shards)
        for i in itertools.count():
            if i >= MINE_LIMIT:
                return None
            sid = f"canary-{worker}-{i}"
            if shard_of(sid, n) == shard:
                return sid

    def _pin(self, worker: str, shard: Optional[int]) -> Optional[_Pin]:
        """Create (or re-create) the pinned session for one worker."""
        sid = self._mine_sid(worker, shard)
        if sid is None:
            return None
        body = {
            "tenant": TENANT, "sid": sid, "height": self.side,
            "width": self.side, "seed": SEED, "density": DENSITY,
            "rule": RULE,
        }
        status, doc = self._http("POST", "/boards", body)
        if status == 400 and "exists" in str(doc.get("error", "")):
            # A stale pin from a previous prober life owns the id: the
            # canary namespace is ours — reclaim and re-seed.
            self._http("DELETE", f"/boards/{sid}", None)
            status, doc = self._http("POST", "/boards", body)
        if status != 201:
            return None
        board = random_grid(
            (self.side, self.side), density=DENSITY, seed=SEED
        )
        pin = _Pin(worker, shard, sid, board, time.monotonic())
        served = doc.get("digest")
        if served is not None and served != pin.digests[0]:
            # Corrupt from birth — certify the create answer too.
            self._fail(pin, 0, pin.digests[0], served, trace=None)
            return None
        return pin

    def _probe(self, pin: _Pin) -> str:
        span = self.tracer.start(
            "serve.canary", node=None, worker=pin.worker, sid=pin.sid,
        )
        t0 = time.perf_counter()
        with span:
            status, doc = self._http(
                "POST", f"/boards/{pin.sid}/step",
                {"steps": 1, "_trace": span.ctx},
            )
            latency = time.perf_counter() - t0
            if status == 200:
                epoch = int(doc.get("epoch", -1))
                expected = pin.expect(epoch) if epoch >= 0 else None
                served = doc.get("digest")
                if expected is not None and served == expected:
                    pin.last_ok = time.monotonic()
                    self._m_probes.labels(outcome="ok").inc()
                    self._m_latency.observe(latency)
                    span.set(outcome="ok", epoch=epoch,
                             latency_s=round(latency, 6))
                    return "ok"
                span.set(outcome="mismatch", epoch=epoch)
                self._fail(pin, epoch, expected, served,
                           trace=span.trace_id)
                return "mismatch"
            if status == 429:
                # Retryable by contract (failover window / draining):
                # not corruption; staleness keeps the clock honest.
                self._m_probes.labels(outcome="rejected").inc()
                span.set(outcome="rejected", status=status)
                return "rejected"
            if status == 404:
                # Honest loss: drop the pin; next round re-creates it.
                self._pins.pop(pin.worker, None)
                self._m_probes.labels(outcome="lost").inc()
                span.set(outcome="lost", status=status)
                return "lost"
            span.set(outcome="error", status=status)
            self._count("error", failure=True, worker=pin.worker,
                        sid=pin.sid, trace=span.trace_id)
            return "error"

    # -- plumbing -------------------------------------------------------------

    def _fail(self, pin: _Pin, epoch: int, expected, served,
              trace: Optional[str]) -> None:
        self._count(
            "mismatch", failure=True, worker=pin.worker, sid=pin.sid,
            trace=trace, epoch=epoch, expected=expected, served=served,
        )
        # A corrupt answer means the board is untrusted from here on:
        # drop the pin so the next round re-seeds from epoch 0 and keeps
        # watching (one alarm per corrupt answer, not one forever).
        self._pins.pop(pin.worker, None)

    def _count(self, outcome: str, *, failure: bool, worker: str,
               sid: str = "", trace: Optional[str] = None,
               **fields) -> None:
        self._m_probes.labels(outcome=outcome).inc()
        if not failure:
            return
        self._m_failures.inc()
        if self.events is not None:
            self.events.emit(
                "canary_fail", outcome=outcome, worker=worker, sid=sid,
                trace=trace or "", **fields,
            )
        flight = getattr(self.tracer, "flight", None)
        if flight is not None:
            flight.dump("canary_fail", node="canary")

    def _http(self, method: str, path: str, body) -> tuple:
        data = (
            json.dumps(body).encode("utf-8") if body is not None else None
        )
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, self._json(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, self._json(e.read())
        except Exception as e:  # noqa: BLE001 — timeouts/conn refuse → probe error
            return 0, {"error": repr(e)}

    @staticmethod
    def _json(raw: bytes) -> dict:
        try:
            doc = json.loads(raw.decode("utf-8"))
            return doc if isinstance(doc, dict) else {"value": doc}
        except Exception:  # noqa: BLE001 — a torn body is an error document
            return {"error": "unparseable response"}
