"""Cross-tenant memoized macro-stepping: the Hashlife-grade serve fast path.

The serving plane's boards are small, numerous, and HIGHLY repetitive:
guns, oscillators, still lifes, and dead space dominate real traffic, and
thousands of tenants seed from overlapping pattern libraries.  Hashlife's
macro-cell theorem (``ops/macroblock.py``) turns that repetition into a
fast path that works for EVERY outer-totalistic rule — including the
nonlinear ones the XOR fast-forward plane (``ops/fastforward.py``) cannot
touch: a B-sided block's content determines its T-sided center (T = B/2)
for S = B/4 generations, so

    (rule, canonical block payload)  →  center tile after S epochs

is a pure function, memoizable in a content-addressed cache shared across
ALL sessions of ALL tenants in the process.  One tenant's glider gun
warms the cache for every other tenant running the same rule.

The engine advances memo-eligible step jobs in **macro-rounds** of S
epochs each, lockstep across the tick's tasks:

0. the WHOLE pre-round board is hashed against the board-chain cache
   (:class:`BoardMemo` — Hashlife's top-of-the-quadtree move): a board on
   a periodic orbit, settled ash, or a twin tenant's trajectory advances
   the full S epochs for one packbits+crc of the board, skipping every
   per-block step below;
1. otherwise the board tiles into T-sided result tiles; each tile's
   toroidal B-sided context block is extracted in one gather;
2. all-zero contexts under a no-B0 rule short-circuit to zero centers —
   no hashing, no assembly (dead space is the dominant win on structured
   boards);
3. the rest hash (crc32 bucket + full-payload compare — collisions cost a
   memcmp, never a wrong answer) and hit or miss the shared cache;
4. the round's unique misses — deduplicated ACROSS tasks, so two tenants
   missing the same block pay the device once — batch into ONE vmapped
   device call (``serve/batch.memo_block_step_fn``, rule masks as traced
   operands, batch dim padded to a power of two);
5. results scatter back into the cache and every task assembles its next
   board from centers; digest lanes fold from per-block contributions
   (``ops/digest.BlockLaneCache``) instead of an O(board) re-mix.

Overhead discipline (the PR 9 contract — observability/auxiliary planes
stay within ~5% of the work they watch): hashing is the only cost a
hostile board can force.  Per-session warmup probes the cache ungated for
``serve_memo_warmup`` macro-rounds; after that, a round whose hit rate
falls below ``serve_memo_hit_floor`` aborts the task to the dense path
immediately (misses NOT paid), and ``serve_memo_disable_after``
consecutive low rounds disable memoization for the session outright — a
high-entropy random board degrades to one crc32 pass per probe round,
then to zero.

Trust, but verify: memoized trajectories are sampled against direct
iteration through the digest plane.  Every ``serve_memo_certify_every``-th
macro-round of a session (and always its first), the pre-round board is
ALSO advanced S epochs by the dense batched kernel and the two digests
compared — ``gol_memo_certify_total`` / ``gol_memo_certify_mismatches_total``
count the verdicts, and a mismatch raises a loud event + flight dump,
commits the DIRECT board (the trusted one), and drops the session to the
dense path for good.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from akka_game_of_life_tpu.ops import digest as odigest
from akka_game_of_life_tpu.ops import macroblock as mblock
from akka_game_of_life_tpu.serve import batch as sbatch

__all__ = ["MemoCache", "MemoEngine", "MemoTask"]

# Per-entry bookkeeping estimate charged against serve_memo_max_mb beyond
# the payload/center bytes themselves: dict slot, key tuple, two bytes
# objects' headers, the pop int.  An estimate on purpose — the bound
# exists to stop unbounded growth, not to account the allocator.
_ENTRY_OVERHEAD = 160


class _Entry:
    """One memoized macro-step result: context payload → decoded center.

    The center ships decoded (read-only uint8) because hits are the hot
    path — assembly must be a reshape/transpose away, never an unpackbits
    per tile per round.  ``center_payload`` re-encodes the center once at
    insert so whole-board digests can key the block-lane cache by center
    CONTENT (maximal reuse: the same still life at the same origin folds
    identical lanes whatever context produced it)."""

    __slots__ = ("center", "center_payload", "pop", "nbytes")

    def __init__(self, payload: bytes, center: np.ndarray, states: int):
        center = np.ascontiguousarray(center, dtype=np.uint8)
        center.setflags(write=False)
        self.center = center
        self.center_payload = mblock.encode_blocks(
            center[None, :, :], states
        )[0]
        self.pop = int((center == 1).sum())
        self.nbytes = (
            len(payload)
            + center.nbytes
            + len(self.center_payload)
            + _ENTRY_OVERHEAD
        )


class MemoCache:
    """The content-addressed macro-cell store, shared across every session
    and tenant of a router.

    Keys are ``(rule_operands, crc32(payload), payload)``: the crc is the
    cheap bucket hash (``ops/macroblock.block_key``), and the payload
    bytes ride the key so equality — Python's own within-bucket compare —
    resolves crc collisions by full content, never by trusting the hash.
    Byte-bounded LRU: eviction pops the coldest entry until under
    ``max_bytes``; an evicted block just recomputes on next miss, so
    tightness costs device time, never correctness.  Thread-safe (the
    ticker owns the write path, but /cost and metrics read concurrently).
    """

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, key: tuple) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e

    def insert(self, key: tuple, center: np.ndarray, states: int) -> _Entry:
        e = _Entry(key[2], center, states)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = e
            self.bytes += e.nbytes
            while self.bytes > self.max_bytes and len(self._entries) > 1:
                _, cold = self._entries.popitem(last=False)
                self.bytes -= cold.nbytes
                self.evictions += 1
        return e

    def stats(self) -> dict:
        with self._lock:
            probes = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / probes) if probes else 0.0,
            }


class _BoardEntry:
    """One whole-board macro-step chain link: canonical pre-round board
    payload → (board, lanes, pop) after S epochs."""

    __slots__ = ("board", "lanes", "pop", "nbytes")

    def __init__(self, payload: bytes, board: np.ndarray, lanes, pop: int):
        self.board = board
        self.lanes = lanes
        self.pop = pop
        self.nbytes = len(payload) + board.nbytes + 8 + _ENTRY_OVERHEAD


class BoardMemo:
    """The second memo level: whole-board macro-step chaining.

    Hashlife's superpower is not the leaf blocks — it is memoizing at the
    TOP of the quadtree, so a board on a periodic orbit (a gun, an
    oscillator garden, settled ash) advances a full macro-round per hash
    lookup of the whole board.  Same key discipline as :class:`MemoCache`
    (rule operands + crc bucket + full payload, plus the board shape —
    bit-packing erases geometry, and a 32x64 board must never answer a
    64x32 probe), same byte-bounded LRU, same collision story.  The block
    cache underneath stays the workhorse for boards that share structure
    without repeating exactly; this level turns exact recurrence — the
    steady state of every bounded Life board — into O(bytes) per round.
    """

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _BoardEntry]" = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, key: tuple) -> Optional[_BoardEntry]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e

    def insert(
        self, key: tuple, board: np.ndarray, lanes, pop: int
    ) -> None:
        board = np.ascontiguousarray(board, dtype=np.uint8)
        board.setflags(write=False)
        e = _BoardEntry(key[2], board, lanes, pop)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = e
            self.bytes += e.nbytes
            while self.bytes > self.max_bytes and len(self._entries) > 1:
                _, cold = self._entries.popitem(last=False)
                self.bytes -= cold.nbytes
                self.evictions += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "board_entries": len(self._entries),
                "board_bytes": self.bytes,
                "board_hits": self.hits,
                "board_misses": self.misses,
                "board_evictions": self.evictions,
            }


class _SessionMemo:
    """Per-session adaptive state, stored on the Session object so it dies
    (and its history with it) when the session does."""

    __slots__ = ("rounds", "hits", "misses", "low_streak", "disabled")

    def __init__(self) -> None:
        self.rounds = 0
        self.hits = 0
        self.misses = 0
        self.low_streak = 0
        self.disabled = False


class MemoTask:
    """One step job riding the memo phase: the snapshot it was planned
    against, the working board the rounds evolve, and the commit payload
    (lanes/pop) the router writes back."""

    __slots__ = (
        "job", "sess", "board0", "epoch0", "board", "rounds_total",
        "rounds_done", "state", "fell_back", "lanes", "pop",
    )

    def __init__(self, job, sess, board0, epoch0, rounds_total, state):
        self.job = job
        self.sess = sess
        self.board0 = board0
        self.epoch0 = epoch0
        self.board = board0
        self.rounds_total = rounds_total
        self.rounds_done = 0
        self.state = state
        self.fell_back = False
        self.lanes: Optional[np.ndarray] = None
        self.pop = 0


class MemoEngine:
    """The macro-stepping engine one :class:`SessionRouter` owns.

    Pure compute: ``plan_tasks`` partitions a tick's snapshots into memo
    tasks and dense passthroughs, ``run`` advances the tasks by macro-
    rounds.  Table commits stay in the router (its lock, its optimistic
    write-back discipline) — the engine never touches the session table.
    """

    def __init__(
        self,
        config,
        *,
        registry,
        tracer,
        events=None,
        size_classes: Sequence[int] = sbatch.DEFAULT_SIZE_CLASSES,
        cache: Optional[MemoCache] = None,
    ) -> None:
        self.block = int(config.serve_memo_block)
        self.steps = self.block // 4
        self.hit_floor = float(config.serve_memo_hit_floor)
        self.warmup = int(config.serve_memo_warmup)
        self.disable_after = int(config.serve_memo_disable_after)
        self.certify_every = int(config.serve_memo_certify_every)
        self.size_classes = tuple(size_classes)
        budget = int(config.serve_memo_max_mb) << 20
        self.cache = cache if cache is not None else MemoCache(budget)
        # The whole-board chain level rides an eighth of the byte budget:
        # its entries are fat (a full board each) but an orbit needs only
        # period-many of them, and the block cache stays the workhorse
        # for cross-board sharing.
        self.board_cache = BoardMemo(max(budget >> 3, 1 << 20))
        self.lane_cache = odigest.BlockLaneCache()
        self.tracer = tracer
        self.events = events
        m = registry
        self._m_hits = m.counter(
            "gol_serve_memo_hits_total", labelnames=("tenant",)
        )
        self._m_misses = m.counter(
            "gol_serve_memo_misses_total", labelnames=("tenant",)
        )
        self._m_epochs = m.counter(
            "gol_serve_memo_epochs_total", labelnames=("tenant",)
        )
        self._m_entries = m.gauge("gol_serve_memo_entries")
        self._m_bytes = m.gauge("gol_serve_memo_bytes")
        self._m_evictions = m.counter("gol_serve_memo_evictions_total")
        self._m_hit_rate = m.gauge("gol_serve_memo_hit_rate")
        self._m_disables = m.counter("gol_serve_memo_disables_total")
        self._m_certify = m.counter("gol_memo_certify_total")
        self._m_certify_bad = m.counter("gol_memo_certify_mismatches_total")
        self._evictions_pub = 0  # counter is monotonic; cache stat is too
        # Block-equivalent probe totals across BOTH memo levels (a board
        # hit serves every one of its blocks), matching the per-tenant
        # counters — the global hit-rate gauge derives from these.
        self._hits_eq = 0
        self._misses_eq = 0
        # The cost observatory's /cost doc grows a serve_memo section so
        # cache economics attribute alongside compile/device spend.
        from akka_game_of_life_tpu.obs.programs import register_section

        register_section("serve_memo", self._section_stats)

    def _section_stats(self) -> dict:
        """The /cost ``serve_memo`` section: block-cache economics plus
        the whole-board chain level's, one flat numeric dict so the cost
        observatory can merge it across cluster members."""
        return {**self.cache.stats(), **self.board_cache.stats()}

    # The per-tenant instruments whose children the router must reclaim
    # when a tenant's last session drops (the exposition-growth contract
    # _drop_locked enforces for every tenant-labelled serve metric).
    @property
    def tenant_instruments(self) -> tuple:
        return (self._m_hits, self._m_misses, self._m_epochs)

    # -- planning -------------------------------------------------------------

    def eligible(self, sess) -> bool:
        """Memo-plane eligibility for a session's geometry and state (the
        rule is always totalistic on this plane)."""
        state = sess.memo
        if state is not None and state.disabled:
            return False
        return mblock.plan(sess.height, sess.width, self.block) is not None

    def plan_tasks(
        self, entries: List[tuple]
    ) -> Tuple[List[MemoTask], List[tuple]]:
        """Partition a tick's ``(job, sess, board, epoch0)`` snapshots into
        memo tasks (jobs worth ≥ 1 macro-round on eligible sessions) and
        dense passthroughs."""
        tasks: List[MemoTask] = []
        passthrough: List[tuple] = []
        for entry in entries:
            job, sess, board, epoch0 = entry
            rounds = job.steps // self.steps
            if rounds < 1 or not self.eligible(sess):
                passthrough.append(entry)
                continue
            if sess.memo is None:
                sess.memo = _SessionMemo()
            tasks.append(
                MemoTask(job, sess, board, epoch0, rounds, sess.memo)
            )
        return tasks, passthrough

    # -- the macro-round loop -------------------------------------------------

    def run(self, tasks: List[MemoTask]) -> None:
        """Advance every task as far as memoization carries it (mutating
        tasks in place): lockstep macro-rounds with cross-task miss
        deduplication, one device call per round.  A task that falls back
        (low hit rate, certify mismatch) keeps the rounds it completed —
        the router routes its remainder dense."""
        while True:
            active = [
                t
                for t in tasks
                if not t.fell_back and t.rounds_done < t.rounds_total
            ]
            if not active:
                break
            self._run_round(active)
        probes = self._hits_eq + self._misses_eq
        if probes:
            self._m_hit_rate.set(self._hits_eq / probes)
        self._m_entries.set(len(self.cache))
        self._m_bytes.set(self.cache.bytes)
        ev = self.cache.evictions + self.board_cache.evictions
        if ev > self._evictions_pub:
            self._m_evictions.inc(ev - self._evictions_pub)
            self._evictions_pub = ev

    def _run_round(self, active: List[MemoTask]) -> None:
        # Phase 1: extract + hash + look up, per task.  Misses are only
        # PLANNED here (per-task), committed to the round batch in phase 2
        # after the task passes its hit-rate gate — a gated task must not
        # charge the device for blocks only it wanted.
        plans = []  # (task, plan, rule_ops, slots, board_key)
        for t in active:
            sess = t.sess
            p = mblock.plan(sess.height, sess.width, self.block)
            rule_ops = sbatch.rule_operands(sess.rule)
            # Whole-board chain level first: a board seen before (periodic
            # orbit, settled ash, a twin tenant one round behind) advances
            # the entire macro-round for one hash of the board — no
            # extraction, no per-block probes, no assembly.
            bp = mblock.encode_blocks(t.board[None], rule_ops[2])[0]
            bkey = (rule_ops, mblock.block_key(bp), bp, t.board.shape)
            be = self.board_cache.lookup(bkey)
            if be is not None:
                board_pre = t.board
                t.board = be.board
                t.lanes = be.lanes
                t.pop = be.pop
                st = t.state
                st.hits += p.n_tiles  # one board hit = every block served
                st.low_streak = 0
                self._hits_eq += p.n_tiles
                self._m_hits.labels(tenant=sess.tenant).inc(p.n_tiles)
                t.rounds_done += 1
                st.rounds += 1
                self._m_epochs.labels(tenant=sess.tenant).inc(self.steps)
                if self.certify_every > 0 and (
                    st.rounds % self.certify_every == 1
                    or self.certify_every == 1
                ):
                    self._certify(t, board_pre, rule_ops)
                continue
            ctx = mblock.extract_contexts(t.board, p)
            live = ctx.reshape(p.n_tiles, -1).any(axis=1)
            if rule_ops[0] & 1:
                # B0 rules birth from dead space: no zero shortcut.
                live[:] = True
            idx = np.flatnonzero(live)
            payloads = (
                mblock.encode_blocks(ctx[idx], rule_ops[2])
                if idx.size
                else []
            )
            # slots[j] is tile j's resolution: None → zero center,
            # _Entry → hit, (key, block) → miss pending device compute.
            slots: List[object] = [None] * p.n_tiles
            n_hit = int(p.n_tiles - idx.size)  # zero tiles are free hits
            n_miss = 0
            for j, payload in zip(idx, payloads):
                key = (rule_ops, mblock.block_key(payload), payload)
                e = self.cache.lookup(key)
                if e is None:
                    slots[j] = (key, np.ascontiguousarray(ctx[j]))
                    n_miss += 1
                else:
                    slots[j] = e
                    n_hit += 1
            st = t.state
            st.hits += n_hit
            st.misses += n_miss
            self._hits_eq += n_hit
            self._misses_eq += n_miss
            self._m_hits.labels(tenant=sess.tenant).inc(n_hit)
            self._m_misses.labels(tenant=sess.tenant).inc(n_miss)
            rate = n_hit / p.n_tiles
            if st.rounds >= self.warmup and rate < self.hit_floor:
                # Post-warmup gate, BEFORE misses are paid: the round cost
                # on a hostile board is the crc pass above, nothing more.
                st.low_streak += 1
                t.fell_back = True
                if st.low_streak >= self.disable_after and not st.disabled:
                    st.disabled = True
                    self._m_disables.inc()
                    if self.events is not None:
                        self.events.emit(
                            "memo_disabled",
                            sid=sess.sid,
                            tenant=sess.tenant,
                            rounds=st.rounds,
                            hit_rate=round(rate, 4),
                        )
                continue
            st.low_streak = 0
            plans.append((t, p, rule_ops, slots, bkey))
        if not plans:
            return

        # Phase 2: ONE device call for the round's unique misses.
        misses: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        for _, _, _, slots, _ in plans:
            for s in slots:
                if type(s) is tuple:
                    misses.setdefault(s[0], s[1])
        computed: Dict[tuple, _Entry] = {}
        if misses:
            keys = list(misses)
            n = len(keys)
            n_pad = sbatch.next_pow2(n)
            blocks = np.zeros(
                (n_pad, self.block, self.block), dtype=np.uint8
            )
            birth = np.zeros(n_pad, dtype=np.uint32)
            survive = np.zeros(n_pad, dtype=np.uint32)
            states = np.full(n_pad, 2, dtype=np.int32)
            for i, key in enumerate(keys):
                blocks[i] = misses[key]
                birth[i], survive[i], states[i] = key[0]
            centers = np.asarray(
                sbatch.memo_block_step_fn(self.block)(
                    blocks, birth, survive, states
                )
            )
            for i, key in enumerate(keys):
                computed[key] = self.cache.insert(
                    key, centers[i], key[0][2]
                )

        # Phase 3: assemble each surviving task's next board; lanes fold
        # from per-center contributions, population from entry pops.
        for t, p, rule_ops, slots, bkey in plans:
            sess = t.sess
            tile = p.tile
            board_pre = t.board
            stack = np.zeros(
                (p.n_tiles, tile, tile), dtype=np.uint8
            )
            parts = []
            pop = 0
            origins = p.origins()
            for j, s in enumerate(slots):
                if s is None:
                    continue  # zero center: zero lanes, zero pop
                e = s if isinstance(s, _Entry) else computed[s[0]]
                stack[j] = e.center
                pop += e.pop
                parts.append(
                    self.lane_cache.block_lanes(
                        e.center_payload, e.center, origins[j], p.width
                    )
                )
            t.board = p.assemble(stack)
            t.lanes = odigest.merge_lanes(parts)
            t.pop = pop
            t.rounds_done += 1
            t.state.rounds += 1
            self._m_epochs.labels(tenant=sess.tenant).inc(self.steps)
            if self.certify_every > 0 and (
                t.state.rounds % self.certify_every == 1
                or self.certify_every == 1
            ):
                self._certify(t, board_pre, rule_ops)
            if not t.fell_back:
                # Chain the round at the board level — but never a result
                # certification just rejected (the block path was wrong;
                # caching its output would launder the corruption).
                self.board_cache.insert(bkey, t.board, t.lanes, t.pop)

    # -- sampled certification ------------------------------------------------

    def _certify(self, t: MemoTask, board_pre: np.ndarray, rule_ops) -> None:
        """Advance the pre-round board S epochs on the DENSE batched kernel
        (batch of one) and compare digests with the memoized result.  A
        mismatch is a kernel/cache bug signal: loud event + flight dump,
        the direct board wins the commit, and the session leaves the memo
        plane for good."""
        sess = t.sess
        cls = sbatch.size_class(sess.height, sess.width, self.size_classes)
        if cls is None:  # unreachable on this plane; never certify-skip silently
            cls = sbatch.next_pow2(max(sess.height, sess.width))
        length = sbatch.next_pow2(self.steps)
        boards = np.zeros((1, cls, cls), dtype=np.uint8)
        boards[0, : sess.height, : sess.width] = board_pre
        out, lanes = sbatch.batch_step_fn(cls, length)(
            boards,
            np.asarray([rule_ops[0]], dtype=np.uint32),
            np.asarray([rule_ops[1]], dtype=np.uint32),
            np.asarray([rule_ops[2]], dtype=np.int32),
            np.asarray([sess.height], dtype=np.int32),
            np.asarray([sess.width], dtype=np.int32),
            np.asarray([self.steps], dtype=np.int32),
        )
        direct_lanes = np.asarray(lanes, dtype=np.uint32)[0]
        self._m_certify.inc()
        if odigest.value(direct_lanes) == odigest.value(t.lanes):
            return
        self._m_certify_bad.inc()
        direct = np.asarray(out)[0, : sess.height, : sess.width].copy()
        if self.events is not None:
            self.events.emit(
                "memo_certify_mismatch",
                sid=sess.sid,
                tenant=sess.tenant,
                rule=sess.rule.rulestring(),
                epoch=t.epoch0 + t.rounds_done * self.steps,
                memo=odigest.format_digest(odigest.value(t.lanes)),
                direct=odigest.format_digest(odigest.value(direct_lanes)),
            )
        flight = getattr(self.tracer, "flight", None)
        if flight is not None:
            flight.dump("memo_certify_mismatch", node="serve")
        # The direct board is the trusted one: commit it, keep the round
        # (it DID advance S epochs), and retire the session from memo.
        t.board = direct
        t.lanes = direct_lanes
        t.pop = int((direct == 1).sum())
        t.fell_back = True
        if not t.state.disabled:
            t.state.disabled = True
            self._m_disables.inc()
