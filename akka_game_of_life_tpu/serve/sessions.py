"""Session table + job queue: the router that feeds the batched engine.

One :class:`SessionRouter` owns every tenant board in the process: a
session table (tenant id, rule, seed, epoch, idle-TTL eviction), a bounded
job queue, and a ticker thread that drains the queue in **ticks** — each
tick groups pending step jobs by size class, pads them into one
``[B, C, C]`` stack, and advances the whole group in ONE device program
(:mod:`akka_game_of_life_tpu.serve.batch`), scattering boards, epochs, and
per-board digest lanes back into the table.

Admission control is enforced at the table edge, never inside the engine,
and always answers instead of wedging:

- ``serve_max_sessions`` — session-count cap (per process);
- ``serve_max_cells``    — aggregate live-cell budget across sessions (the
  batch-memory resource a count cap alone cannot bound);
- ``serve_queue_depth``  — pending-job bound; a full queue REJECTS the new
  job (the caller's 429 + retry) rather than dropping a queued one —
  dropping would lose a request whose client is already blocked on it;
- ``serve_max_steps``    — per-request epoch bound for QUEUED jobs (the
  scan length is the ticker's unit of fairness); beyond it, XOR-linear
  rule sessions answer through the O(log T) fast-forward path
  (``ops/fastforward.py``) and everything else is refused ``max_steps``.

Rejections raise :class:`AdmissionError` with a machine-readable
``reason`` (the HTTP layer maps it to 429 and the reason rides the
``gol_serve_rejects_total{reason}`` counter).  Boards live host-side as
plain uint8 arrays between ticks — sessions are small by design (the size
classes top out well below the single-board kernels' territory), and the
host copy is what GET returns without touching the device.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.obs import get_registry
from akka_game_of_life_tpu.obs.tracing import get_tracer
from akka_game_of_life_tpu.ops import digest as odigest, fastforward
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule
from akka_game_of_life_tpu.runtime.wire import pack_tile, unpack_tile
from akka_game_of_life_tpu.serve import batch as sbatch
from akka_game_of_life_tpu.utils.patterns import random_grid

# A step request abandoned by the engine (ticker died, close() raced) must
# never block its client thread forever; this is the server-side bound on
# one job's queue wait + batch run.
JOB_TIMEOUT_S = 120.0
# After JOB_TIMEOUT_S, a job still IN the queue is cancelled (removed —
# guaranteed never applied, the client's retry is safe); a job already
# riding a launched batch gets this much extra grace to land, because its
# write-back cannot be recalled.
JOB_GRACE_S = 60.0

# Bound on CONCURRENT fast-forward jumps (the linear-rule step fast path
# runs on caller threads, not the ticker): each jump is milliseconds on
# serve-class boards, but without a cap N simultaneous over-bound requests
# would run N certify+jump computations at once and starve the ticker's
# CPU — the very monopolization the max_steps bound exists to prevent.
# Over-limit requests get the retryable 429 (reason queue_full), never a
# wedge.
FF_MAX_CONCURRENT = 8

# Tenant ids label metrics (gol_serve_*{tenant=...}); they must be short
# and tame or a client could mint unbounded exposition series from junk.
_TENANT_MAX = 64
_TENANT_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._:-"
)


class AdmissionError(Exception):
    """A request refused by admission control (HTTP 429).  ``reason`` is
    machine-readable: ``max_sessions`` | ``max_cells`` | ``queue_full`` |
    ``draining`` | ``max_steps`` (a step request beyond ``serve_max_steps``
    for a session whose rule cannot fast-forward — linear-rule sessions
    bypass the bound via the O(log T) fast path instead) | ``migrating``
    (the session's shard is mid-migration on the cluster plane — always
    retryable; the cluster frontend holds such ops and replays them at the
    shard's new owner, so tenants never see this reason) | ``failover``
    (the session's shard is mid-promotion after its worker died — always
    retryable: the board provably resumes at its last replicated epoch,
    and the retry lands at the promoted replica)."""

    def __init__(self, reason: str, detail: str, trace_link=None) -> None:
        super().__init__(detail)
        self.reason = reason
        # Optional causal pointer: the trace ctx (trace_id/span_id dict) of
        # the span that CAUSED this rejection — a failover 429 links to the
        # serve.promote span it is waiting on, so the 429'd request's trace
        # clicks through to the promotion.
        self.trace_link = trace_link


def shard_of(sid: str, n_shards: int) -> int:
    """Stable session-shard hash (crc32 — identical across processes and
    restarts).  Lives here because BOTH halves of the cluster serve plane
    route by it: the frontend picks owners, and a worker answering
    SHARD_PREPARE recomputes its OWN resident membership for the shard
    (the authoritative freeze set — a frontend-snapshotted sid list could
    miss a create that was in flight when the migration was planned)."""
    import zlib

    return zlib.crc32(sid.encode("utf-8")) % n_shards


def rendezvous_pick(key: str, names):
    """Highest-random-weight pick: the name maximizing crc32(f"{key}:{n}")
    (name as the deterministic tiebreak).  The ONE placement function
    behind shard replicas, tiled-chunk replicas, and the federation's
    shard→frontend slice map — a membership change re-homes only the keys
    that must move, never ~all of them the way a modulo ring would.
    Returns None on an empty candidate pool."""
    import zlib

    pool = list(names)
    if not pool:
        return None
    return max(
        pool, key=lambda n: (zlib.crc32(f"{key}:{n}".encode("utf-8")), n)
    )


def validate_create(tenant, rule, height: int, width: int, density: float):
    """Shared create-request validation (raises ValueError, the HTTP
    400); returns the resolved Rule.  ONE implementation on purpose: the
    single-process router and the cluster plane must accept exactly the
    same requests, or the two surfaces drift."""
    tenant = str(tenant)
    if not tenant or len(tenant) > _TENANT_MAX or not (
        set(tenant) <= _TENANT_OK
    ):
        raise ValueError(
            f"tenant must be 1..{_TENANT_MAX} chars of [A-Za-z0-9._:-] "
            f"(it labels metrics), got {tenant!r}"
        )
    rule_r = resolve_rule(rule)
    sbatch.rule_operands(rule_r)  # totalistic-only; raises ValueError
    if height < 1 or width < 1:
        raise ValueError(f"board must be positive, got {height}x{width}")
    if not (0.0 <= density <= 1.0):
        raise ValueError(f"density {density} must be in [0, 1]")
    return rule_r


@dataclasses.dataclass
class Session:
    """One tenant board and its serving state."""

    sid: str
    tenant: str
    rule: Rule
    height: int
    width: int
    seed: int
    density: float
    board: np.ndarray  # (height, width) uint8, host-side
    lanes: np.ndarray  # (2,) uint32 digest lanes of `board`
    population: int = 0  # live (state 1) cells of `board`, kept in lockstep
    epoch: int = 0
    created: float = 0.0
    last_used: float = 0.0
    # Per-session memo-plane state (serve/memo.py), lazily attached by the
    # engine; None when the plane is off or the session never qualified.
    # Deliberately NOT exported/imported: a migrated or promoted session
    # restarts with fresh adaptive state against its new router's cache.
    memo: Optional[object] = None

    @property
    def digest(self) -> int:
        return odigest.value(self.lanes)

    def snapshot(self, *, with_board: bool = True) -> dict:
        """The GET document (board copied so a caller can't mutate the
        table's array).  ``with_board=False`` skips the O(h·w) copy for
        summary paths — list() runs under the router lock, and touching
        every board there would stall the ticker for all tenants
        (``population`` is cached at create/write-back for the same
        reason, never scanned here)."""
        doc = {
            "id": self.sid,
            "tenant": self.tenant,
            "rule": self.rule.rulestring(),
            "height": self.height,
            "width": self.width,
            "seed": self.seed,
            "epoch": self.epoch,
            "population": self.population,
            "digest": odigest.format_digest(self.digest),
        }
        if with_board:
            doc["board"] = self.board.copy()
        return doc


@dataclasses.dataclass
class _Job:
    sid: str
    steps: int
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: Optional[Tuple[int, int]] = None  # (epoch, digest)
    error: Optional[BaseException] = None
    # Completion callback for async submitters (the cluster serve worker
    # plane coalesces results back onto the wire instead of blocking a
    # thread per job).  Fired exactly once, after result/error is set and
    # ``done`` fires, never under the router lock.
    on_done: Optional[Callable[["_Job"], None]] = None
    # Queue accounting for the SLO plane: enqueue time (monotonic) stamped
    # at submit, queue wait stamped when the ticker takes the job for a
    # batch — the "how long did admission hold this" half of latency.
    t_enq: float = 0.0
    queue_wait_s: float = 0.0


class SessionRouter:
    """The multi-tenant serving engine: session table + job queue + ticker.

    Thread-safe; constructed from a :class:`SimulationConfig`'s ``serve_*``
    knobs (every knob has a ``--serve-*`` flag —
    ``tools/check_serve_config.py`` lint-enforces the bijection).  The
    ``clock`` injection point exists for TTL tests; ``pause()``/``resume()``
    hold the ticker between batches — the deterministic way to fill the
    queue in backpressure drills (bench_serve's 429 drill)."""

    def __init__(
        self,
        config=None,
        *,
        registry=None,
        tracer=None,
        events=None,
        clock=time.monotonic,
    ) -> None:
        if config is None:
            from akka_game_of_life_tpu.runtime.config import SimulationConfig

            config = SimulationConfig()
        self.config = config
        self.max_sessions = config.serve_max_sessions
        self.max_cells = config.serve_max_cells
        self.queue_depth = config.serve_queue_depth
        self.max_steps = config.serve_max_steps
        self.tick_s = config.serve_tick_s
        self.ttl_s = config.serve_ttl_s
        self.size_classes = sbatch.parse_size_classes(
            config.serve_size_classes
        )
        self.metrics = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self._clock = clock
        # Hot-path instruments resolved once (lookup takes the registry
        # lock); per-tenant children minted on demand.
        self._m_sessions = self.metrics.gauge(
            "gol_serve_sessions", labelnames=("tenant",)
        )
        self._m_cells = self.metrics.gauge("gol_serve_cells")
        self._m_creates = self.metrics.counter(
            "gol_serve_session_creates_total", labelnames=("tenant",)
        )
        self._m_evictions = self.metrics.counter(
            "gol_serve_session_evictions_total"
        )
        self._m_steps = self.metrics.counter(
            "gol_serve_steps_total", labelnames=("tenant",)
        )
        self._m_rejects = self.metrics.counter(
            "gol_serve_rejects_total", labelnames=("reason",)
        )
        self._m_queue = self.metrics.gauge("gol_serve_queue_depth")
        self._m_ff = self.metrics.counter("gol_serve_ff_jumps_total")
        self._m_ff_retries = self.metrics.counter(
            "gol_serve_ff_jump_retries_total"
        )
        self._m_digest_mismatch = self.metrics.counter(
            "gol_digest_mismatches_total"
        )
        self._ff_slots = threading.BoundedSemaphore(FF_MAX_CONCURRENT)
        # Buckets passed explicitly (count-scale, not latency-scale): the
        # registry may be a plain MetricsRegistry without the catalog
        # installed, and _get_or_create would not flag the mismatch.
        from akka_game_of_life_tpu.obs.catalog import RING_BATCH_BUCKETS

        self._m_batch = self.metrics.histogram(
            "gol_serve_batch_boards", buckets=RING_BATCH_BUCKETS
        )
        self._m_tick = self.metrics.histogram("gol_serve_tick_seconds")
        self._m_req = self.metrics.histogram("gol_serve_step_seconds")

        # Cross-tenant memoized macro-stepping (serve/memo.py): one engine
        # + content-addressed cache per router, feeding every tenant.
        self._memo = None
        if getattr(config, "serve_memo", False):
            from akka_game_of_life_tpu.serve.memo import MemoEngine

            self._memo = MemoEngine(
                config,
                registry=self.metrics,
                tracer=self.tracer,
                events=events,
                size_classes=self.size_classes,
            )

        # Drill hook (None in production): called between a fast-forward
        # jump's compute and its commit attempt, so tests can provoke the
        # optimistic-commit retry deterministically (pause the ticker, queue
        # a batch job, let it land inside this window — the blocked-batch
        # drill that certifies gol_serve_ff_jump_retries_total).
        self._drill_ff_precommit: Optional[Callable[[], None]] = None
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._sessions: Dict[str, Session] = {}  # graftlint: guarded-by _lock
        self._cells = 0  # graftlint: guarded-by _lock
        self._queue: deque = deque()  # graftlint: guarded-by _lock
        self._ids = itertools.count(1)
        # Sessions frozen by an in-flight shard migration: present (GETs
        # still answer) but refusing writes with the retryable "migrating"
        # reason, exempt from TTL eviction, until commit drops them or
        # abort unfreezes them.
        self._frozen: set = set()  # graftlint: guarded-by _lock
        # sids of jobs the ticker has taken for the CURRENT batch (between
        # queue drain and scatter-back) — what wait_idle must see beyond
        # the queue, or an export could snapshot a board whose in-flight
        # write-back lands after the transfer and is silently lost.
        self._inflight_sids: set = set()  # graftlint: guarded-by _lock
        self._paused = False  # graftlint: guarded-by _lock
        self._draining = False  # graftlint: guarded-by _lock
        self._stopped = False  # graftlint: guarded-by _lock
        self._ticker = threading.Thread(
            target=self._tick_loop, daemon=True, name="serve-ticker"
        )
        self._ticker.start()

    # -- session lifecycle ---------------------------------------------------

    def create(
        self,
        tenant: str = "default",
        rule="conway",
        height: int = 64,
        width: int = 64,
        seed: int = 0,
        density: float = 0.5,
        with_board: bool = True,
        sid: Optional[str] = None,
    ) -> dict:
        """Admit a new session and seed its board.  Raises ValueError for a
        malformed request (the HTTP 400), AdmissionError when a capacity
        cap refuses it (the HTTP 429).  ``with_board=False`` skips the
        returned doc's O(h·w) board copy — the HTTP 201 deliberately
        carries no cells.  ``sid`` overrides the locally minted id: the
        cluster frontend allocates ids itself (the id's hash picks the
        shard, so the router must honor the id that routed here)."""
        tenant = str(tenant)
        rule = validate_create(tenant, rule, height, width, density)
        if sbatch.size_class(height, width, self.size_classes) is None:
            raise ValueError(
                f"board {height}x{width} exceeds the largest size class "
                f"({self.size_classes[-1]}); this plane serves small "
                f"boards — run big ones standalone"
            )
        # Admission is checked BEFORE the O(h·w) board generation so a
        # saturated plane sheds rejected creates cheaply (429 is the
        # overload path), then re-checked at insert — the lock is released
        # in between and a racing create may have taken the last slot.
        with self._lock:
            self._admit_locked(height, width)
        board = random_grid((height, width), density=density, seed=seed)
        lanes = odigest.digest_dense_np(board)
        population = int((board == 1).sum())
        with self._lock:
            self._admit_locked(height, width)
            if sid is not None and sid in self._sessions:
                raise ValueError(f"session id {sid!r} already exists")
            now = self._clock()
            sess = Session(
                sid=sid if sid is not None else f"b{next(self._ids):08x}",
                tenant=tenant,
                rule=rule,
                height=height,
                width=width,
                seed=seed,
                density=density,
                board=board,
                lanes=lanes,
                population=population,
                created=now,
                last_used=now,
            )
            self._sessions[sess.sid] = sess
            self._cells += height * width
            self._m_cells.set(self._cells)
            self._m_sessions.labels(tenant=sess.tenant).inc()
            self._m_creates.labels(tenant=sess.tenant).inc()
        # Snapshot OUTSIDE the lock: nobody can step this session before
        # its id is returned, and the O(h·w) board copy must not stall
        # the ticker or concurrent requests.
        return sess.snapshot(with_board=with_board)

    def get(self, sid: str) -> dict:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                raise KeyError(sid)
            sess.last_used = self._clock()
            return sess.snapshot()

    def list(self) -> List[dict]:
        with self._lock:
            return [
                sess.snapshot(with_board=False)
                for sess in self._sessions.values()
            ]

    def delete(self, sid: str) -> None:
        with self._lock:
            if sid in self._frozen:
                # A delete that raced a shard migration: the authoritative
                # copy is in flight — the cluster plane retries it at the
                # shard's post-commit owner.
                self._reject(
                    "migrating",
                    f"session {sid} is mid-shard-migration; retry",
                )
            self._drop_locked(sid, evicted=False)

    def _drop_locked(self, sid: str, *, evicted: bool) -> None:
        """Remove a session (lock held).  An in-flight step job for it
        completes against the ticker's snapshot and its write-back is
        skipped — the client still gets the stepped result."""
        sess = self._sessions.pop(sid, None)
        if sess is None:
            raise KeyError(sid)
        self._cells -= sess.height * sess.width
        self._m_cells.set(self._cells)
        self._m_sessions.labels(tenant=sess.tenant).dec()
        if not any(
            s.tenant == sess.tenant for s in self._sessions.values()
        ):
            # Last session of this tenant: reclaim its metric children, or
            # a create/delete loop over fresh tenant strings would grow
            # the exposition without bound.
            memo_insts = (
                self._memo.tenant_instruments if self._memo is not None else ()
            )
            for inst in (
                self._m_sessions, self._m_creates, self._m_steps,
            ) + memo_insts:
                inst.remove(tenant=sess.tenant)
        if evicted:
            self._m_evictions.inc()

    def _reject(self, reason: str, detail: str) -> None:
        self._m_rejects.labels(reason=reason).inc()
        raise AdmissionError(reason, detail)

    def _admit_locked(self, height: int, width: int) -> None:
        """The create-side admission gate (lock held): closed router,
        drain, session cap, cell budget — raising instead of wedging."""
        if self._stopped:
            raise RuntimeError("router is closed")
        if self._draining:
            self._reject("draining", "router is draining for shutdown")
        if len(self._sessions) >= self.max_sessions:
            self._reject(
                "max_sessions",
                f"session cap {self.max_sessions} reached",
            )
        if self._cells + height * width > self.max_cells:
            self._reject(
                "max_cells",
                f"cell budget {self.max_cells} would be exceeded "
                f"({self._cells} in use, {height * width} asked)",
            )

    # -- stepping ------------------------------------------------------------

    def submit(
        self,
        sid: str,
        steps: int = 1,
        on_done: Optional[Callable[[_Job], None]] = None,
    ) -> _Job:
        """Admit one step request and return its job handle WITHOUT
        blocking on the result — the async half of :meth:`step`.  The
        cluster serve worker plane submits every step of a coalesced
        SERVE_OPS frame this way and lets ``on_done`` route completions
        back onto the wire instead of parking one thread per job.

        Admission refusals (AdmissionError/KeyError/ValueError/
        RuntimeError) raise synchronously — the request never became a
        job.  An over-bound linear-rule request runs the O(log T)
        fast-forward path INLINE on the calling thread (milliseconds on
        serve-class boards) and returns an already-completed job whose
        ``error`` carries any jump failure."""
        if steps < 1:
            raise ValueError(f"steps {steps} must be >= 1")
        if int(steps).bit_length() > fastforward.MAX_SPAN_BITS:
            # A 400, not an admission question: beyond the span ceiling
            # even the fast path refuses (its per-jump program count is
            # bounded by the span's bit length — the DoS guard).
            raise ValueError(
                f"steps {steps} exceeds the fast-forward span ceiling "
                f"(2^{fastforward.MAX_SPAN_BITS})"
            )
        with self._lock:
            if self._stopped:
                # The ticker is gone: enqueueing would strand the caller
                # on JOB_TIMEOUT_S; fail now like create() does.
                raise RuntimeError("router is closed")
            sess = self._sessions.get(sid)
            if sess is None:
                # Looked up BEFORE the drain gate: an unknown id is a
                # terminal 404, not a retryable 429.
                raise KeyError(sid)
            if sid in self._frozen:
                self._reject(
                    "migrating",
                    f"session {sid} is mid-shard-migration; retry",
                )
            if self._draining:
                self._reject("draining", "router is draining for shutdown")
            fast = steps > self.max_steps
            if fast:
                linear = sess.rule.is_linear
                if not linear or not self.config.ff_enabled:
                    why = (
                        "fast-forward is disabled (ff_enabled=False)"
                        if linear
                        else f"rule {sess.rule} is not XOR-linear"
                    )
                    self._reject(
                        "max_steps",
                        f"steps {steps} over serve_max_steps="
                        f"{self.max_steps} and {why}; bound the request "
                        f"(the scan length is the ticker's unit of "
                        f"fairness) or use a linear rule",
                    )
            else:
                if len(self._queue) >= self.queue_depth:
                    self._reject(
                        "queue_full",
                        f"step queue depth {self.queue_depth} reached",
                    )
                sess.last_used = self._clock()
                job = _Job(
                    sid=sid, steps=steps, on_done=on_done,
                    t_enq=self._clock(),
                )
                self._queue.append(job)
                self._m_queue.set(len(self._queue))
                self._wake.notify_all()
                return job
        # Fast path, inline: bypasses the ticker queue, so queue_depth
        # cannot bound it — the slot cap does, with the same retryable
        # 429 contract.
        if not self._ff_slots.acquire(blocking=False):
            self._reject(
                "queue_full",
                f"fast-forward concurrency bound "
                f"({FF_MAX_CONCURRENT}) reached; retry",
            )
        job = _Job(sid=sid, steps=steps, on_done=on_done)
        try:
            job.result = self._fast_forward_step(sess, steps)
        except BaseException as e:  # noqa: BLE001 — carried to the waiter
            job.error = e
        finally:
            self._ff_slots.release()
        self._finish(job)
        return job

    def step(self, sid: str, steps: int = 1) -> Tuple[int, int]:
        """Advance a session by ``steps`` epochs; blocks until the batch
        that carried the job lands.  Returns (epoch, digest).  Raises
        KeyError (404), ValueError (400), AdmissionError (429).

        ``steps`` beyond ``serve_max_steps`` is an *admission* question,
        not a validity one: an XOR-linear rule session takes the O(log T)
        fast-forward path (``ops/fastforward.py`` — answers n=1,000,000
        in milliseconds instead of queueing 10⁶ ticks), everything else
        is refused 429 ``max_steps`` so one giant request can never
        monopolize the ticker for every other tenant."""
        t0 = time.perf_counter()
        job = self.submit(sid, steps)
        if not job.done.wait(JOB_TIMEOUT_S):
            with self._lock:
                try:
                    self._queue.remove(job)
                    cancelled = True
                    self._m_queue.set(len(self._queue))
                except ValueError:
                    cancelled = False
            if cancelled:
                # Still queued → removed before any batch saw it: the
                # board did NOT advance, a client retry is safe.
                raise TimeoutError(
                    f"step job for {sid} timed out in queue (cancelled; "
                    f"board not advanced)"
                )
            # Already riding a launched batch: its write-back cannot be
            # recalled, so give it bounded grace to land rather than
            # reporting failure for epochs that WILL apply.
            if not job.done.wait(JOB_GRACE_S):
                raise TimeoutError(f"step job for {sid} timed out mid-batch")
        if job.error is not None:
            raise job.error
        self._m_req.observe(time.perf_counter() - t0)
        # Hand the measured queue wait up to the HTTP edge's SLO line
        # (same thread: step() blocks the request thread on the job).
        from akka_game_of_life_tpu.obs import slo as _slo

        _slo.note_queue_wait(job.queue_wait_s if job.t_enq else None)
        return job.result

    def tenant_of(self, sid: str) -> Optional[str]:
        """The owning tenant, or None for an unknown id — the cheap
        attribution lookup the SLO access log uses (never raises)."""
        with self._lock:
            sess = self._sessions.get(sid)
            return sess.tenant if sess is not None else None

    def _finish(self, job: _Job) -> None:
        """Fire a job's completion — the done event, then the async
        callback.  Called with result/error already assigned and NEVER
        under the router lock (callbacks enqueue wire replies and must not
        serialize behind, or deadlock against, table operations)."""
        job.done.set()
        if job.on_done is not None:
            try:
                job.on_done(job)
            except Exception:  # noqa: BLE001 — a callback bug must not kill the ticker
                pass

    def _fast_forward_step(self, sess: Session, steps: int) -> Tuple[int, int]:
        """The linear-rule fast path: jump ``steps`` epochs in O(log steps)
        device programs, bypassing the ticker queue entirely.

        The jump computes OUTSIDE every lock (holding the router lock
        across device work would starve all tenants) against a snapshot
        of (board, epoch); the write-back is an optimistic commit — if a
        concurrently queued batch job's scatter-back landed in between,
        the jump recomputes from the new state (bounded retries; jumps
        are milliseconds on serve-class boards, batches serialize one job
        per session per tick, so contention is rare and shrinking).  A
        session deleted mid-jump still gets its stepped result, like a
        mid-batch delete.  Each jump is jump-vs-iterate digest-certified
        on a ``ff_certify_steps`` sample before it commits."""
        for _ in range(8):
            with self._lock:
                if self._sessions.get(sess.sid) is not sess:
                    raise KeyError(sess.sid)
                board0, epoch0 = sess.board, sess.epoch
                sess.last_used = self._clock()
            cert = min(steps, self.config.ff_certify_steps)
            if cert:
                try:
                    fastforward.certify_jump(board0, sess.rule, cert)
                except RuntimeError:
                    # The documented kernel-bug signal: same counter the
                    # Simulation surface ticks on jump-vs-iterate
                    # divergence, so serve-path math failures alert too.
                    self._m_digest_mismatch.inc()
                    raise
            out = fastforward.fast_forward_np(board0, sess.rule, steps)
            lanes = odigest.digest_dense_np(out)
            population = int((out == 1).sum())
            hook = self._drill_ff_precommit
            if hook is not None:
                # Deterministic interleave point for the retry drill: a
                # test parks here while a blocked batch's scatter-back
                # lands, then observes the commit race below.
                hook()
            with self._lock:
                if self._sessions.get(sess.sid) is not sess:
                    # Deleted mid-jump: the client still gets its result;
                    # the table write-back is skipped (the mid-batch
                    # delete contract).
                    return epoch0 + steps, odigest.value(lanes)
                if sess.board is board0 and sess.epoch == epoch0:
                    sess.board = out
                    sess.lanes = lanes
                    sess.population = population
                    sess.epoch = epoch0 + steps
                    sess.last_used = self._clock()
                    self._m_steps.labels(tenant=sess.tenant).inc(steps)
                    self._m_ff.inc()
                    return sess.epoch, odigest.value(lanes)
            # A batch write-back raced the commit: loop and recompute
            # from the session's new state.  Counted so the (rare, bounded)
            # recompute-on-race residue of the optimistic commit is
            # observable in production, not just documented.
            self._m_ff_retries.inc()
        raise TimeoutError(
            f"fast-forward for {sess.sid} kept losing the commit race to "
            f"batched step jobs; retry"
        )

    # -- drill hooks ---------------------------------------------------------

    def pause(self) -> None:
        """Hold the ticker between batches (jobs queue up; admission still
        answers).  The backpressure-drill hook — bench_serve and the tests
        use it to fill the queue deterministically."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._wake.notify_all()

    # -- shard migration (the cluster serve plane's worker half) -------------

    def freeze_sessions(self, sids) -> None:
        """Freeze sessions for an in-flight shard migration: writes refuse
        with the retryable ``migrating`` reason, TTL eviction skips them,
        reads still answer.  Unknown ids are ignored (already evicted —
        the export simply ships fewer sessions)."""
        with self._lock:
            self._frozen.update(s for s in sids if s in self._sessions)

    def wait_idle(self, sids, timeout: float = 10.0) -> bool:
        """The freeze barrier: block until no queued OR in-flight job
        references ``sids`` — admitted jobs complete (their write-backs
        belong in the exported state), new ones are already refused.
        Bounded by REAL time like :meth:`drain`, and for the same reason."""
        sids = set(sids)
        deadline = time.monotonic() + timeout  # graftlint: waive GL-HAZ04 -- pairs with the real time.sleep pacing below; a frozen injected test clock must not unbound migration
        while time.monotonic() < deadline:
            with self._lock:
                busy = self._inflight_sids | {j.sid for j in self._queue}
                if not (busy & sids):
                    return True
            time.sleep(0.01)
        return False

    def export_sessions(self, sids) -> List[dict]:
        """Snapshot sessions as self-contained wire payloads (``pack_tile``
        boards + digest lanes) — the TRANSFER half of a shard migration.
        Boards pack OUTSIDE the lock: writers only ever replace board
        references, and the sessions are frozen anyway."""
        with self._lock:
            rows = [
                (s, s.board, s.lanes)
                for s in (self._sessions.get(sid) for sid in sids)
                if s is not None
            ]
        return [
            {
                "sid": sess.sid,
                "tenant": sess.tenant,
                "rule": sess.rule.rulestring(),
                "height": sess.height,
                "width": sess.width,
                "seed": sess.seed,
                "density": sess.density,
                "epoch": sess.epoch,
                "population": sess.population,
                "state": pack_tile(board),
                "digest": [int(lanes[0]), int(lanes[1])],
            }
            for sess, board, lanes in rows
        ]

    def unfreeze_sessions(self, sids) -> None:
        """Roll a shard migration back: the sessions never left."""
        with self._lock:
            self._frozen.difference_update(sids)

    def drop_sessions(self, sids) -> None:
        """COMMIT: the shard's sessions now live on the destination —
        release them here (cells/gauges/tenant children), not as
        evictions."""
        with self._lock:
            for sid in sids:
                self._frozen.discard(sid)
                if sid in self._sessions:
                    self._drop_locked(sid, evicted=False)

    def import_sessions(self, payloads: List[dict]) -> None:
        """Install migrated sessions (the destination half of a shard
        move).  Deliberately bypasses the admission caps: cluster-wide
        admission is the frontend's budget, already charged when these
        sessions were created — a move must never bounce off the local
        backstop while both copies transiently exist."""
        rows = []
        for p in payloads:
            board = unpack_tile(p["state"])
            lanes = np.asarray(
                [int(p["digest"][0]), int(p["digest"][1])], dtype=np.uint32
            )
            rows.append((p, board, lanes, int((board == 1).sum())))
        with self._lock:
            if self._stopped:
                raise RuntimeError("router is closed")
            now = self._clock()
            for p, board, lanes, pop in rows:
                if p["sid"] in self._sessions:
                    # A re-delivered adopt (frontend retry): replace, never
                    # double-count.
                    self._drop_locked(p["sid"], evicted=False)
                sess = Session(
                    sid=p["sid"],
                    tenant=p["tenant"],
                    rule=resolve_rule(p["rule"]),
                    height=int(p["height"]),
                    width=int(p["width"]),
                    seed=int(p["seed"]),
                    density=float(p["density"]),
                    board=board,
                    lanes=lanes,
                    population=int(p.get("population", pop)),
                    epoch=int(p["epoch"]),
                    created=now,
                    last_used=now,
                )
                self._sessions[sess.sid] = sess
                self._cells += sess.height * sess.width
                self._m_cells.set(self._cells)
                self._m_sessions.labels(tenant=sess.tenant).inc()

    # -- the tick loop -------------------------------------------------------

    def _tick_loop(self) -> None:
        while True:
            with self._lock:
                while not self._stopped and (
                    self._paused or not self._queue
                ):
                    # Bounded wait so idle routers still sweep TTLs.
                    self._wake.wait(timeout=0.25)
                    if not self._paused:
                        self._evict_idle_locked()
                if self._stopped:
                    failed = self._fail_pending_locked(
                        RuntimeError("router closed")
                    )
                    taken = None
                else:
                    # Sweep here too: a router under sustained load never
                    # sits in the idle wait above.
                    self._evict_idle_locked()
                    taken, failed = self._take_jobs_locked()
                    self._inflight_sids = {j.sid for j in taken}
            for job in failed:
                self._finish(job)
            if taken is None:
                return
            if taken:
                t0 = time.perf_counter()
                try:
                    with self.tracer.span("serve.tick", jobs=len(taken)):
                        self._run_tick(taken)
                finally:
                    with self._lock:
                        self._inflight_sids = set()
                dt = time.perf_counter() - t0
                self._m_tick.observe(dt)
                if self.tick_s > 0 and dt < self.tick_s:
                    # Pacing floor: at most one batch launch per tick_s.
                    time.sleep(self.tick_s - dt)
            else:
                with self._lock:
                    self._inflight_sids = set()

    def _take_jobs_locked(self) -> Tuple[List[_Job], List[_Job]]:
        """Drain this tick's jobs: at most ONE job per session (a second
        pending step for the same board serializes into the next tick so
        each job's result is the state after exactly its own steps).
        Returns (taken, dead) — dead-session jobs carry their KeyError but
        are finished by the caller OUTSIDE the lock (callback discipline)."""
        taken: List[_Job] = []
        dead: List[_Job] = []
        rest: deque = deque()
        seen = set()
        now = self._clock()
        while self._queue:
            job = self._queue.popleft()
            if job.sid not in self._sessions:
                job.error = KeyError(job.sid)
                dead.append(job)
                continue
            if job.sid in seen:
                rest.append(job)
                continue
            seen.add(job.sid)
            if job.t_enq:
                job.queue_wait_s = max(0.0, now - job.t_enq)
            taken.append(job)
        self._queue = rest
        self._m_queue.set(len(self._queue))
        return taken, dead

    def _fail_pending_locked(self, err: BaseException) -> List[_Job]:
        """Error out every queued job; the caller fires completions
        outside the lock."""
        failed: List[_Job] = []
        while self._queue:
            job = self._queue.popleft()
            job.error = err
            failed.append(job)
        self._m_queue.set(0)
        return failed

    def _evict_idle_locked(self) -> None:
        if self.ttl_s <= 0:
            return
        now = self._clock()
        # A session with an ADMITTED queued job is never idle — evicting
        # it would 404 a client already blocked on that job, breaking the
        # "a queued job always completes" admission contract.  Frozen
        # sessions belong to an in-flight shard migration: their clock
        # stopped with their traffic, so the sweep must not race the
        # commit that is about to move them.
        busy = {job.sid for job in self._queue}
        for sid in [
            s.sid
            for s in self._sessions.values()
            if s.sid not in busy
            and s.sid not in self._frozen
            and now - s.last_used > self.ttl_s
        ]:
            self._drop_locked(sid, evicted=True)

    def _run_tick(self, jobs: List[_Job]) -> None:
        """Advance this tick's jobs: the memo phase first (macro-rounds of
        the Hashlife fast path for eligible jobs — serve/memo.py), then
        every job's dense remainder grouped by size class, one device
        program per group, results scattered back.  A failed batch fails
        its jobs, never the ticker."""
        snapshots: List[Tuple[_Job, Session, np.ndarray, int]] = []
        dead: List[_Job] = []
        with self._lock:
            for job in jobs:
                sess = self._sessions.get(job.sid)
                if sess is None:
                    job.error = KeyError(job.sid)
                    dead.append(job)
                    continue
                # Snapshot the board reference AND epoch: writers only
                # ever REPLACE session boards, so the references are
                # stable outside the lock — and the scatter-back commits
                # only if this exact snapshot is still the session state
                # (a fast-forward jump may land mid-batch).
                snapshots.append((job, sess, sess.board, sess.epoch))
        for job in dead:
            self._finish(job)
        if self._memo is not None:
            entries = self._memo_phase(snapshots)
        else:
            entries = [
                (job, sess, board, epoch0, job.steps)
                for job, sess, board, epoch0 in snapshots
            ]
        groups: Dict[
            int, List[Tuple[_Job, Session, np.ndarray, int, int]]
        ] = {}
        for entry in entries:
            sess = entry[1]
            cls = sbatch.size_class(
                sess.height, sess.width, self.size_classes
            )
            groups.setdefault(cls, []).append(entry)
        from akka_game_of_life_tpu.obs.programs import get_programs

        programs = get_programs()
        before = programs.programs_total
        for cls, centries in sorted(groups.items()):
            try:
                self._run_class_batch(cls, centries)
            except Exception as e:  # noqa: BLE001 — jobs fail, ticker lives
                for job, _, _, _, _ in centries:
                    job.error = e
                    self._finish(job)
        if (
            snapshots
            and not programs.warm
            and programs.programs_total == before
        ):
            # A full tick advanced real jobs without compiling any new
            # program: the router's program set is its steady state.  Arm
            # the storm detector — from here on, a novel (class, length)
            # compile is a latency cliff worth an alert + flight dump.
            programs.mark_warm()

    def _memo_phase(
        self, snapshots: List[Tuple[_Job, Session, np.ndarray, int]]
    ) -> List[Tuple[_Job, Session, np.ndarray, int, int]]:
        """Run the tick's memo-eligible jobs through macro-rounds
        (serve/memo.py), commit what memoization carried, and return the
        dense entries — ``(job, sess, board, epoch0, nsteps)`` — that
        remain: passthroughs, remainders (steps % S), and the full jobs
        of tasks that advanced nothing.

        Commit discipline mirrors the batch scatter-back: a memoized
        board writes back only if the planned snapshot is still the
        session state; a raced task (a fast-forward jump landed, or the
        session was deleted mid-phase) keeps its memo progress for the
        CLIENT — its remainder entry carries the memoized board relative
        to the original snapshot — but the table write is skipped (the
        board-identity check in the dense scatter-back can never pass
        for it, since the memoized array reference was never published).
        """
        tasks, passthrough = self._memo.plan_tasks(snapshots)
        dense: List[Tuple[_Job, Session, np.ndarray, int, int]] = [
            (job, sess, board, epoch0, job.steps)
            for job, sess, board, epoch0 in passthrough
        ]
        if not tasks:
            return dense
        try:
            with self.tracer.span("serve.memo", tasks=len(tasks)):
                self._memo.run(tasks)
        except Exception:  # noqa: BLE001 — an engine bug degrades to dense
            return dense + [
                (t.job, t.sess, t.board0, t.epoch0, t.job.steps)
                for t in tasks
            ]
        s_macro = self._memo.steps
        finished: List[_Job] = []
        with self._lock:
            for t in tasks:
                advanced = t.rounds_done * s_macro
                if advanced == 0:
                    dense.append(
                        (t.job, t.sess, t.board0, t.epoch0, t.job.steps)
                    )
                    continue
                sess = t.sess
                if (
                    self._sessions.get(t.job.sid) is sess
                    and sess.board is t.board0
                    and sess.epoch == t.epoch0
                ):
                    sess.board = t.board
                    sess.lanes = t.lanes
                    sess.population = t.pop
                    sess.epoch = t.epoch0 + advanced
                    sess.last_used = self._clock()
                    self._m_steps.labels(tenant=sess.tenant).inc(advanced)
                rem = t.job.steps - advanced
                if rem == 0:
                    t.job.result = (
                        t.epoch0 + advanced, odigest.value(t.lanes)
                    )
                    finished.append(t.job)
                else:
                    dense.append(
                        (t.job, sess, t.board, t.epoch0 + advanced, rem)
                    )
        for job in finished:
            self._finish(job)
        return dense

    def _run_class_batch(
        self,
        cls: int,
        entries: List[Tuple[_Job, Session, np.ndarray, int, int]],
    ) -> None:
        b_real = len(entries)
        length = sbatch.next_pow2(
            max(nsteps for _, _, _, _, nsteps in entries)
        )
        b_pad = sbatch.next_pow2(b_real)
        boards = np.zeros((b_pad, cls, cls), dtype=np.uint8)
        birth = np.zeros(b_pad, dtype=np.uint32)
        survive = np.zeros(b_pad, dtype=np.uint32)
        states = np.full(b_pad, 2, dtype=np.int32)
        hs = np.ones(b_pad, dtype=np.int32)
        ws = np.ones(b_pad, dtype=np.int32)
        ns = np.zeros(b_pad, dtype=np.int32)
        for i, (job, sess, board, _, nsteps) in enumerate(entries):
            boards[i, : sess.height, : sess.width] = board
            birth[i], survive[i], states[i] = sbatch.rule_operands(sess.rule)
            hs[i], ws[i] = sess.height, sess.width
            ns[i] = nsteps
        out, lanes = sbatch.batch_step_fn(cls, length)(
            boards, birth, survive, states, hs, ws, ns
        )
        out = np.asarray(out)
        lanes = np.asarray(lanes, dtype=np.uint32)
        self._m_batch.observe(b_real)
        # Slice-copies and popcounts are O(Σ h·w) host work — done OUTSIDE
        # the lock so scatter-back never stalls concurrent create/step/get.
        results = [
            (
                out[i, : sess.height, : sess.width].copy(),
                lanes[i],
            )
            for i, (_, sess, _, _, _) in enumerate(entries)
        ]
        pops = [int((board == 1).sum()) for board, _ in results]
        with self._lock:
            for (job, sess, board0, epoch0, nsteps), (
                new_board, new_lanes,
            ), pop in zip(entries, results, pops):
                if (
                    self._sessions.get(job.sid) is sess
                    and sess.board is board0
                    and sess.epoch == epoch0
                ):
                    sess.board = new_board
                    sess.lanes = new_lanes
                    sess.population = pop
                    sess.epoch = epoch0 + nsteps
                    sess.last_used = self._clock()
                    self._m_steps.labels(tenant=sess.tenant).inc(nsteps)
                else:
                    # Deleted mid-batch — or a fast-forward jump committed
                    # between this batch's gather and scatter-back (the
                    # jump's epochs must never be clobbered by a stale
                    # batch result).  Either way the client still gets its
                    # result, computed from the snapshot it asked about;
                    # the table write-back is skipped, and so is the
                    # per-tenant counter — _drop_locked may just have
                    # reclaimed this tenant's metric children, and
                    # incrementing here would re-mint a leaked child for a
                    # gone tenant.
                    pass
                job.result = (epoch0 + nsteps, odigest.value(new_lanes))
        # Completions fire after the table writes are released: callbacks
        # (the cluster plane's wire replies) must never run under the lock.
        for job, _, _, _, _ in entries:
            self._finish(job)

    def drain(self, timeout: float = 30.0) -> bool:
        """Refuse NEW work and run the already-admitted queue dry (bounded)
        — the graceful half of shutdown: an admitted job completes, it is
        never failed with 'router closed' just because the operator sent
        SIGTERM.  Returns True when the queue emptied in time."""
        with self._lock:
            self._draining = True
            self._wake.notify_all()
        # Bounded by REAL time on purpose: the loop paces with time.sleep,
        # so the deadline must tick with it — on the injected clock a
        # frozen TTL-test clock would turn this bounded shutdown wait into
        # an infinite hang.
        deadline = time.monotonic() + timeout  # graftlint: waive GL-HAZ04 -- the real-time bound pairs with the real time.sleep pacing below; a frozen injected test clock must not unbound shutdown
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue:
                    return True
            time.sleep(0.05)
        return False

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        """The /healthz contribution: live table + queue facts."""
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "cells": self._cells,
                "queue_depth": len(self._queue),
                "max_sessions": self.max_sessions,
                "max_cells": self.max_cells,
                "size_classes": list(self.size_classes),
            }

    def close(self) -> None:
        """Stop the ticker and fail any still-pending jobs loudly."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._wake.notify_all()
        self._ticker.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
