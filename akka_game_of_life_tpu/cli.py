"""Command-line entry points — the ``Run.scala`` capability layer.

The reference ships two mains: ``RunFrontend [port]`` and ``RunBackend
[port]`` (``Run.scala:15-54,56-65``), with every other knob in
``application.conf``.  Here one CLI exposes the same layered precedence
(defaults < config file < flags) plus a standalone mode the reference lacks:

    python -m akka_game_of_life_tpu run --rule conway --height 256 --width 256
    python -m akka_game_of_life_tpu frontend --port 2551 ...
    python -m akka_game_of_life_tpu backend --port 0 ...
"""

from __future__ import annotations

import argparse
import contextlib
import sys
from typing import Optional, Sequence

from akka_game_of_life_tpu.runtime.config import load_config, parse_duration

# The --kernel choice surface.  A literal (not an import of
# runtime.config.KERNEL_CHOICES) on purpose: the drift lints parse both
# files textually so they can run before the environment exists —
# graftlint GL-CFG06 enforces that this tuple, the config tuple, and the
# docs/OPERATIONS.md "Kernel selection" table never diverge.
_KERNEL_CHOICES = (
    "auto",
    "dense",
    "bitpack",
    "pallas",
    "matmul",
)


def _apply_platform(platform: Optional[str]) -> None:
    """Pin the JAX platform before anything touches devices.

    ``--platform cpu`` (or ``GOL_PLATFORM=cpu``) is the supported way to run
    on the host: plugin registrations done at interpreter boot (e.g. a TPU
    PJRT plugin in sitecustomize) can force ``jax_platforms``, so an env var
    alone is not honored — the config must be updated after jax imports but
    before first backend init.
    """
    import os

    platform = platform or os.environ.get("GOL_PLATFORM")
    if platform and platform != "auto":
        import jax

        jax.config.update("jax_platforms", platform)
    # Every subcommand funnels through here before first backend init —
    # the one spot to arm the persistent compile cache (tunnel compiles
    # cost 20-40 s; re-runs of a seen program load from disk instead).
    from akka_game_of_life_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()


def _add_platform(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="JAX platform to pin (e.g. cpu, tpu, or a PJRT plugin name; "
        "default: auto-detect; GOL_PLATFORM env var is the fallback)",
    )


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="TOML or JSON config file")
    _add_platform(p)
    p.add_argument("--rule", help="rule name or rulestring (B3/S23, /2/3, ...)")
    p.add_argument("--height", type=int)
    p.add_argument("--width", type=int)
    p.add_argument("--density", type=float)
    p.add_argument("--seed", type=int)
    p.add_argument(
        "--pattern",
        help="initial board: a built-in pattern name or a path to a "
        "Golly/LifeWiki .rle file (header rule checked against --rule)",
    )
    p.add_argument("--max-epochs", type=int)
    p.add_argument("--tick", help="wall-clock pacing per epoch (e.g. 3000ms); 0 = free-run")
    p.add_argument("--steps-per-call", type=int)
    p.add_argument(
        "--kernel",
        choices=list(_KERNEL_CHOICES),
        help="stencil kernel: auto picks the Mosaic temporal-blocking pallas "
        "kernel on a real TPU for binary rules, single-device or sharded "
        "over the mesh (bitpack fallback if Mosaic fails), else bitpack "
        "(32 cells/uint32 SWAR) on 32-aligned widths, else dense uint8; "
        "matmul is the banded matrix-multiply (MXU) family — any "
        "box-neighborhood rule incl. radius-R LtL, single-device, "
        "intermediates guard-priced up front (docs/OPERATIONS.md "
        '"MXU stencil path")',
    )
    p.add_argument("--pallas-block-rows", type=int)
    p.add_argument(
        "--pallas-vmem-limit-mb",
        type=int,
        help="Mosaic scoped-VMEM budget override in MB (0 = compiler default "
        "16 MB); block_rows >= 256 at 65536-class widths needs ~20+ MB",
    )
    p.add_argument("--halo-width", type=int)
    p.add_argument("--mesh", help="ROWSxCOLS device mesh, e.g. 4x2")
    p.add_argument("--backend", choices=["tpu", "actor", "actor-native"])
    p.add_argument("--checkpoint-dir")
    p.add_argument("--checkpoint-every", type=int)
    p.add_argument("--checkpoint-format", choices=["npz", "orbax"])
    p.add_argument(
        "--checkpoint-sync",
        action="store_true",
        default=None,
        help="block at each checkpoint until the save is durable (default: "
        "single-process npz saves overlap compute on a writer thread)",
    )
    p.add_argument("--render-every", type=int)
    p.add_argument(
        "--probe-window",
        default=None,
        help="exact-cell probe window printed at render cadence, as "
        "Y0:Y1,X0:X1 (e.g. 8:17,8:44 — the Gosper-gun bbox at offset 8,8); "
        "fetched O(window), usable at 65536²",
    )
    p.add_argument("--render-max-cells", type=int)
    p.add_argument("--metrics-every", type=int)
    p.add_argument(
        "--metrics-file",
        help="dump Prometheus text exposition here at metrics cadence and "
        "on exit (atomic write; scrape-safe)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        help="serve live /metrics (Prometheus text) and /healthz on this "
        "port for the run/frontend roles (0 = off)",
    )
    p.add_argument(
        "--log-events",
        metavar="PATH",
        help="append structured JSONL lifecycle events (crashes, "
        "recoveries, checkpoints, membership) here, with monotonic "
        "timestamps and per-node labels",
    )
    p.add_argument(
        "--trace-file",
        metavar="PATH",
        help="write the run's causally-linked span buffer here as Chrome "
        "trace-event / Perfetto JSON on exit (open in ui.perfetto.dev or "
        "chrome://tracing; the live view is /trace on --metrics-port)",
    )
    p.add_argument(
        "--flight-dir",
        metavar="DIR",
        help="directory for automatic flight-recorder dumps (last N spans+"
        "events) on crashes, redeploys, and SIGTERM (default: artifacts; "
        "empty string disables)",
    )
    p.add_argument(
        "--obs-defer",
        action="store_true",
        default=None,
        help="dispatch cadence observations on device and fetch them one "
        "chunk later, under the next chunk's compute — removes the host "
        "round-trip from the critical path (observer lines for a cadence "
        "point appear one chunk late; values are identical)",
    )
    p.add_argument(
        "--obs-digest",
        action="store_true",
        default=None,
        help="compute the 64-bit on-device board digest at observation "
        "cadence (~8 fetched bytes; printed as digest=<16 hex> on metrics "
        "lines) — O(1)-byte state certification at any board size; on the "
        "frontend role, workers digest tiles locally and the frontend "
        "merges them (see docs/OPERATIONS.md \"Digest certification\")",
    )
    _add_obs_programs(p)
    g = p.add_argument_group(
        "activity-gated sparse stepping",
        "skip the dead parts of the board: O(activity) throughput on "
        "dilute universes (see docs/OPERATIONS.md \"Activity-gated sparse "
        "stepping\"); every --sparse-X flag maps 1:1 onto "
        "SimulationConfig.sparse_X (tools/check_sparse_config.py "
        "lint-enforces the bijection)",
    )
    g.add_argument(
        "--sparse-cluster",
        choices=["on", "off"],
        default=None,
        help="cluster tier (frontend role, shipped to workers in WELCOME): "
        "a tile whose state and halo repeat across a chunk (period 1 or 2) "
        "skips its step, publishes an O(1)-byte same-ring marker, and "
        "suppresses per-chunk PROGRESS pings; a changed neighboring ring "
        "wakes it with zero wrong-state epochs (default off)",
    )
    g.add_argument(
        "--sparse-kernel",
        choices=["on", "off"],
        default=None,
        help="intra-tile tier (run role): a per-block activity bitmap "
        "gates which blocks the stepper advances — a block steps only if "
        "it or a neighbor changed last chunk (default off)",
    )
    g.add_argument(
        "--sparse-block", type=int, default=None, metavar="B",
        help="gating block side in cells (default 128; clamped to the "
        "largest common divisor of the board sides)",
    )
    g.add_argument(
        "--sparse-threshold", type=float, default=None, metavar="F",
        help="dense escape hatch: above this active-block fraction the "
        "chunk runs the plain dense kernel and only the change bitmap is "
        "recomputed (default 0.5)",
    )
    _add_ff(p)
    p.add_argument("--log-file")
    p.add_argument("--inject-faults", action="store_true", default=None)
    p.add_argument(
        "--distributed",
        action="store_true",
        default=None,
        help="initialize the JAX distributed runtime so the mesh spans all "
        "hosts (pod scale); on TPU pods the coordinator/rank flags are "
        "auto-detected, elsewhere set them or GOL_COORDINATOR / "
        "GOL_NUM_PROCESSES / GOL_PROCESS_ID",
    )
    p.add_argument("--coordinator", metavar="HOST:PORT")
    p.add_argument("--num-processes", type=int)
    p.add_argument("--process-id", type=int)


def _add_obs_programs(p: argparse.ArgumentParser) -> None:
    """The compile & device-cost observatory knobs — shared by every role
    that mounts /programs, /cost, and POST /profile (run, frontend, serve).
    Every ``--obs-X`` flag maps 1:1 onto ``SimulationConfig.obs_X``
    (graftlint ``GL-CFG11``)."""
    p.add_argument(
        "--obs-programs",
        choices=["on", "off"],
        default=None,
        help="compile & device-cost observatory (obs/programs.py): the "
        "jit-program ledger behind /programs, /cost, compile-storm alerts, "
        "and workers' COST frames (default: on; off makes registered_jit "
        "a pass-through)",
    )
    p.add_argument(
        "--obs-cost-interval-s",
        metavar="DUR",
        help="cadence of worker COST frames and local device-memory gauge "
        "refreshes (default: 5s)",
    )
    p.add_argument(
        "--obs-profile-max-s",
        metavar="DUR",
        help="longest POST /profile capture window; longer requests are "
        "clamped (default: 30s)",
    )
    p.add_argument(
        "--obs-profile-min-interval-s",
        metavar="DUR",
        help="minimum gap between POST /profile captures; requests inside "
        "it get HTTP 429 (default: 60s; 0 disables the rate limit)",
    )


def _obs_programs_overrides(args: argparse.Namespace) -> dict:
    return {
        "obs_programs": {"on": True, "off": False, None: None}[
            args.obs_programs
        ],
        "obs_cost_interval_s": (
            parse_duration(args.obs_cost_interval_s)
            if args.obs_cost_interval_s is not None
            else None
        ),
        "obs_profile_max_s": (
            parse_duration(args.obs_profile_max_s)
            if args.obs_profile_max_s is not None
            else None
        ),
        "obs_profile_min_interval_s": (
            parse_duration(args.obs_profile_min_interval_s)
            if args.obs_profile_min_interval_s is not None
            else None
        ),
    }


def _add_ff(p: argparse.ArgumentParser) -> None:
    """The logarithmic fast-forward knobs (``ops/fastforward.py``).  Every
    ``--ff-X`` flag maps 1:1 onto ``SimulationConfig.ff_X`` (dashes to
    underscores) — graftlint ``GL-CFG07`` lint-enforces the CLI ↔ config
    ↔ operator-doc bijection."""
    g = p.add_argument_group(
        "logarithmic fast-forward",
        "jump T epochs of an XOR-linear (odd-rule) board in O(log T) "
        "device programs instead of O(T) (see docs/OPERATIONS.md "
        "\"Logarithmic fast-forward\"); non-linear rules are provably "
        "refused, never silently jumped",
    )
    g.add_argument(
        "--ff-enabled",
        choices=["on", "off"],
        default=None,
        help="master switch (default on): off makes Simulation.fast_forward "
        "refuse and the serve plane answer 429 `max_steps` past the "
        "serve_max_steps bound even for linear rules",
    )
    g.add_argument(
        "--ff-certify-steps", type=int, default=None, metavar="T",
        help="jump-vs-iterate digest certification sample per jump "
        "(default 8): min(T, jump span) epochs also run through the "
        "ordinary stepper and the digests must agree; 0 skips (headline-"
        "size timing runs certify via a separate anchor jump instead)",
    )


def _ff_overrides(args: argparse.Namespace) -> dict:
    """``--ff-*`` flags → SimulationConfig override kwargs (None = unset,
    dropped by load_config)."""
    return {
        "ff_enabled": {"on": True, "off": False, None: None}[args.ff_enabled],
        "ff_certify_steps": args.ff_certify_steps,
    }


def _add_ring_plane(p: argparse.ArgumentParser) -> None:
    """The halo data plane's wire-encoding knobs.  Every ``--ring-X`` flag
    maps 1:1 onto ``SimulationConfig.ring_X`` (dashes to underscores) —
    ``tools/check_ring_config.py`` lint-enforces the bijection.  Frontend
    role only: the policy is cluster config, shipped to workers in WELCOME."""
    g = p.add_argument_group(
        "halo data plane",
        "wire encoding of the worker-to-worker boundary-ring exchange "
        "(see docs/OPERATIONS.md \"Wire format\")",
    )
    g.add_argument(
        "--ring-pack",
        choices=["on", "off"],
        default=None,
        help="bit-pack binary-rule boundary rings 32 cells/uint32 word on "
        "the wire (~8x fewer payload bytes; default on; multi-state rules "
        "always ride raw uint8)",
    )
    g.add_argument(
        "--ring-batch",
        choices=["on", "off"],
        default=None,
        help="coalesce all rings bound for one peer in an epoch/chunk into "
        "a single PEER_RING_BATCH frame (default on; off = one frame per "
        "ring, the reference's shape)",
    )
    g.add_argument(
        "--ring-queue-depth",
        type=int,
        default=None,
        metavar="N",
        help="bound on each per-peer async send queue; a full queue drops "
        "oldest entries (recovered by halo re-pulls) instead of blocking "
        "the step loop",
    )


def _ring_plane_overrides(args: argparse.Namespace) -> dict:
    """``--ring-*`` flags → SimulationConfig override kwargs (empty entries
    are dropped by load_config's None filtering)."""
    on_off = {"on": True, "off": False, None: None}
    return {
        "ring_pack": on_off[args.ring_pack],
        "ring_batch": on_off[args.ring_batch],
        "ring_queue_depth": args.ring_queue_depth,
    }


def _add_rebalance(p: argparse.ArgumentParser) -> None:
    """The elastic rebalancing knobs (``runtime/rebalance.py``).  Every
    ``--rebalance-X`` flag maps 1:1 onto ``SimulationConfig.rebalance_X``
    (dashes to underscores; bare ``--rebalance`` maps to
    ``rebalance_enabled``) — ``tools/check_rebalance_config.py``
    lint-enforces the bijection.  Frontend role only.  Graceful drain
    (SIGTERM on a backend) works regardless; these knobs control the
    AUTOMATIC load-driven migration planning."""
    g = p.add_argument_group(
        "elastic rebalancing",
        "live digest-certified tile migration: mid-run scale-out onto late "
        "joiners and load balancing across workers (see docs/OPERATIONS.md "
        "\"Elastic rebalancing\"; graceful drain is always on)",
    )
    g.add_argument(
        "--rebalance",
        nargs="?",
        choices=["on", "off"],
        const="on",
        default=None,
        help="automatic load-driven tile migration (a late-joining worker "
        "receives tiles mid-run; imbalanced workers even out); bare "
        "--rebalance means on, --rebalance off overrides a config file "
        "that enables it",
    )
    g.add_argument(
        "--rebalance-interval-s", default=None, metavar="DUR",
        help="how often the planner looks for imbalance (e.g. 500ms)",
    )
    g.add_argument(
        "--rebalance-min-gap", type=int, default=None, metavar="N",
        help="migrate when the most- and least-loaded workers differ by "
        "at least N tiles (default 2)",
    )
    g.add_argument(
        "--rebalance-max-inflight", type=int, default=None, metavar="N",
        help="concurrent in-flight migrations (each freezes one tile)",
    )
    g.add_argument(
        "--rebalance-deadline-s", default=None, metavar="DUR",
        help="per-migration deadline; overdue moves roll back to the "
        "source and retry under the jittered backoff policy",
    )


def _rebalance_overrides(args: argparse.Namespace) -> dict:
    """``--rebalance-*`` flags → SimulationConfig override kwargs (empty
    entries are dropped by load_config's None filtering)."""
    return {
        "rebalance_enabled": {"on": True, "off": False, None: None}[
            args.rebalance
        ],
        "rebalance_interval_s": (
            parse_duration(args.rebalance_interval_s)
            if args.rebalance_interval_s is not None
            else None
        ),
        "rebalance_min_gap": args.rebalance_min_gap,
        "rebalance_max_inflight": args.rebalance_max_inflight,
        "rebalance_deadline_s": (
            parse_duration(args.rebalance_deadline_s)
            if args.rebalance_deadline_s is not None
            else None
        ),
    }


def _add_serve(p: argparse.ArgumentParser) -> None:
    """The serving plane's knobs (``serve/``).  Every ``--serve-X`` flag
    maps 1:1 onto ``SimulationConfig.serve_X`` (dashes to underscores) —
    ``tools/check_serve_config.py`` lint-enforces the bijection."""
    g = p.add_argument_group(
        "serving plane",
        "admission control and batched-engine knobs for the multi-tenant "
        "/boards API (see docs/OPERATIONS.md \"Serving plane\")",
    )
    g.add_argument(
        "--serve-max-sessions", type=int, default=None, metavar="N",
        help="session-count cap; creates beyond it answer 429",
    )
    g.add_argument(
        "--serve-max-cells", type=int, default=None, metavar="N",
        help="aggregate live-cell budget across all sessions; creates "
        "that would exceed it answer 429",
    )
    g.add_argument(
        "--serve-queue-depth", type=int, default=None, metavar="N",
        help="pending step-job bound; a full queue answers 429 to NEW "
        "jobs (queued ones always complete)",
    )
    g.add_argument(
        "--serve-max-steps", type=int, default=None, metavar="N",
        help="most generations one step request may ask for",
    )
    g.add_argument(
        "--serve-tick-s", default=None, metavar="DUR",
        help="engine pacing floor: at most one batched device program "
        "per this interval (e.g. 10ms; 0 = free-running)",
    )
    g.add_argument(
        "--serve-ttl-s", default=None, metavar="DUR",
        help="idle-session TTL; untouched sessions are evicted after "
        "this long (e.g. 5m; 0 = never)",
    )
    g.add_argument(
        "--serve-size-classes", default=None, metavar="C1,C2,...",
        help="padded board size classes (square sides, ascending): mixed "
        "shapes bucket into a few compiled programs; bigger boards run "
        "as tiled sessions in cluster mode, and are refused single-"
        "process (default 32,64,128,256)",
    )
    g.add_argument(
        "--serve-cluster",
        choices=["on", "off"],
        default=None,
        help="cluster-sharded serving: this process becomes the tenant-"
        "facing cluster frontend, sessions hash-shard across joined "
        "backend workers (each running its own vmapped batch engine), "
        "session shards migrate under load/drain, and over-class boards "
        "are admitted as tiled sessions (default off)",
    )
    g.add_argument(
        "--serve-shards", type=int, default=None, metavar="N",
        help="virtual session shards — the unit of placement and "
        "migration across workers (default 64)",
    )
    g.add_argument(
        "--serve-tile-chunk", type=int, default=None, metavar="K",
        help="epochs per fan-out round of a tiled (mega-board) session "
        "step; each tile ships a K-wide halo per round trip (default 8)",
    )
    g.add_argument(
        "--serve-tiled-resident",
        choices=["on", "off"],
        default=None,
        help="worker-resident tiled sessions: mega-board chunks install "
        "once on their workers and stay resident across steps, "
        "exchanging O(perimeter) halo strips worker-to-worker per round "
        "instead of shipping O(area) state through the frontend "
        "(default on; off = the ship-per-round baseline)",
    )
    g.add_argument(
        "--serve-tiled-resident-snapshot", type=int, default=None,
        metavar="N",
        help="resident-chunk snapshot cadence in rounds: every Nth "
        "barrier each chunk retains a local snapshot and streams it to "
        "its replica — the certified resume point after a worker loss "
        "(default 4)",
    )
    g.add_argument(
        "--serve-tiled-resident-halo-timeout-s", default=None,
        metavar="DUR",
        help="peer halo strips unacked past this bound retransmit "
        "(default 1s)",
    )
    g.add_argument(
        "--serve-replicate",
        choices=["on", "off"],
        default=None,
        help="session replication & crash failover: every session shard "
        "gets a replica worker the primary streams state to; on worker "
        "loss the frontend promotes the replica (sessions resume from "
        "their last acked replicated epoch, digest-certified) instead of "
        "404ing (default on; degrades to single-copy when no second "
        "placeable worker exists)",
    )
    g.add_argument(
        "--serve-replicate-every", type=int, default=None, metavar="N",
        help="replication epoch cadence: a session re-streams to its "
        "replica after advancing N epochs past the acked watermark "
        "(idle dirty sessions flush regardless; default 8)",
    )
    g.add_argument(
        "--serve-replicate-interval-s", default=None, metavar="DUR",
        help="the primary's replication stream-pass interval (e.g. "
        "250ms; default 0.25s)",
    )
    g.add_argument(
        "--serve-replicate-max-lag-s", default=None, metavar="DUR",
        help="replication lag bound: lag past this is surfaced loudly "
        "(event + /healthz lag_alert_shards; default 30s)",
    )
    g.add_argument(
        "--serve-trace",
        choices=["on", "off"],
        default=None,
        help="per-request serve-plane tracing: mint/adopt a trace id per "
        "HTTP request and propagate it through every serve frame it "
        "causes, so /trace shows serve.request → worker serve.batch "
        "(default on)",
    )
    g.add_argument(
        "--serve-slo-log", default=None, metavar="PATH",
        help="structured JSONL access log: one line per request with "
        "trace id, tenant, route, sid, outcome, queue-wait, latency "
        "(default off; /slo and RED metrics run regardless)",
    )
    g.add_argument(
        "--serve-slo-availability", type=float, default=None, metavar="F",
        help="availability objective the burn-rate tracker scores "
        "against, in (0, 1) (default 0.999)",
    )
    g.add_argument(
        "--serve-slo-latency-ms", type=float, default=None, metavar="MS",
        help="latency objective: requests slower than this are SLO-bad "
        "for the latency objective (default 250)",
    )
    g.add_argument(
        "--serve-slo-fast-window-s", default=None, metavar="DUR",
        help="fast burn-rate window (default 5m)",
    )
    g.add_argument(
        "--serve-slo-slow-window-s", default=None, metavar="DUR",
        help="slow burn-rate window; the alert fires only when BOTH "
        "windows burn (default 1h)",
    )
    g.add_argument(
        "--serve-slo-max-tenants", type=int, default=None, metavar="N",
        help="per-tenant label-cardinality cap: beyond it the least-"
        "recently-seen tenant's series are reclaimed and fold into "
        "tenant=\"~overflow\" (default 64)",
    )
    g.add_argument(
        "--serve-canary",
        choices=["on", "off"],
        default=None,
        help="digest-certified canary prober: a background synthetic "
        "tenant pins one known-orbit session per worker and steps it at "
        "cadence through the real HTTP surface, certifying every answer "
        "against a precomputed oracle (default off)",
    )
    g.add_argument(
        "--serve-canary-interval-s", default=None, metavar="DUR",
        help="canary probe cadence (default 2s)",
    )
    g.add_argument(
        "--serve-canary-side", type=int, default=None, metavar="N",
        help="canary board side, square (default 32)",
    )
    g.add_argument(
        "--serve-memo",
        choices=["on", "off"],
        default=None,
        help="cross-tenant memoized macro-stepping: content-addressed "
        "(rule, block) → center-after-steps cache shared across every "
        "session, with sampled digest certification against direct "
        "iteration (default off)",
    )
    g.add_argument(
        "--serve-memo-block", type=int, default=None, metavar="B",
        help="macro-cell context block side (power of two >= 16); each "
        "macro-round advances B/4 epochs (default 64)",
    )
    g.add_argument(
        "--serve-memo-max-mb", type=int, default=None, metavar="MB",
        help="memo cache byte budget, LRU beyond it (default 256)",
    )
    g.add_argument(
        "--serve-memo-hit-floor", type=float, default=None, metavar="F",
        help="post-warmup per-round tile hit-rate floor below which a "
        "session's round aborts to the dense path (default 0.25)",
    )
    g.add_argument(
        "--serve-memo-warmup", type=int, default=None, metavar="N",
        help="ungated probe macro-rounds per session before the hit "
        "floor applies (default 16)",
    )
    g.add_argument(
        "--serve-memo-disable-after", type=int, default=None, metavar="N",
        help="consecutive below-floor rounds that disable memoization "
        "for the session (default 3)",
    )
    g.add_argument(
        "--serve-memo-certify-every", type=int, default=None, metavar="N",
        help="certify every Nth macro-round per session against the "
        "dense kernel by digest (0 = never; default 64)",
    )


def _serve_overrides(args: argparse.Namespace) -> dict:
    """``--serve-*`` flags → SimulationConfig override kwargs (empty
    entries are dropped by load_config's None filtering)."""
    on_off = {"on": True, "off": False, None: None}
    return {
        "serve_max_sessions": args.serve_max_sessions,
        "serve_max_cells": args.serve_max_cells,
        "serve_queue_depth": args.serve_queue_depth,
        "serve_max_steps": args.serve_max_steps,
        "serve_tick_s": (
            parse_duration(args.serve_tick_s)
            if args.serve_tick_s is not None
            else None
        ),
        "serve_ttl_s": (
            parse_duration(args.serve_ttl_s)
            if args.serve_ttl_s is not None
            else None
        ),
        "serve_size_classes": args.serve_size_classes,
        "serve_cluster": on_off[args.serve_cluster],
        "serve_shards": args.serve_shards,
        "serve_tile_chunk": args.serve_tile_chunk,
        "serve_tiled_resident": on_off[args.serve_tiled_resident],
        "serve_tiled_resident_snapshot": args.serve_tiled_resident_snapshot,
        "serve_tiled_resident_halo_timeout_s": (
            parse_duration(args.serve_tiled_resident_halo_timeout_s)
            if args.serve_tiled_resident_halo_timeout_s is not None
            else None
        ),
        "serve_replicate": on_off[args.serve_replicate],
        "serve_replicate_every": args.serve_replicate_every,
        "serve_replicate_interval_s": (
            parse_duration(args.serve_replicate_interval_s)
            if args.serve_replicate_interval_s is not None
            else None
        ),
        "serve_replicate_max_lag_s": (
            parse_duration(args.serve_replicate_max_lag_s)
            if args.serve_replicate_max_lag_s is not None
            else None
        ),
        "serve_trace": on_off[args.serve_trace],
        "serve_slo_log": args.serve_slo_log,
        "serve_slo_availability": args.serve_slo_availability,
        "serve_slo_latency_ms": args.serve_slo_latency_ms,
        "serve_slo_fast_window_s": (
            parse_duration(args.serve_slo_fast_window_s)
            if args.serve_slo_fast_window_s is not None
            else None
        ),
        "serve_slo_slow_window_s": (
            parse_duration(args.serve_slo_slow_window_s)
            if args.serve_slo_slow_window_s is not None
            else None
        ),
        "serve_slo_max_tenants": args.serve_slo_max_tenants,
        "serve_canary": on_off[args.serve_canary],
        "serve_canary_interval_s": (
            parse_duration(args.serve_canary_interval_s)
            if args.serve_canary_interval_s is not None
            else None
        ),
        "serve_canary_side": args.serve_canary_side,
        "serve_memo": on_off[args.serve_memo],
        "serve_memo_block": args.serve_memo_block,
        "serve_memo_max_mb": args.serve_memo_max_mb,
        "serve_memo_hit_floor": args.serve_memo_hit_floor,
        "serve_memo_warmup": args.serve_memo_warmup,
        "serve_memo_disable_after": args.serve_memo_disable_after,
        "serve_memo_certify_every": args.serve_memo_certify_every,
    }


def _add_frontend_federation(p: argparse.ArgumentParser) -> None:
    """The frontend-federation knobs (``serve/federation.py``).  Every
    ``--frontend-X`` flag maps 1:1 onto ``SimulationConfig.frontend_X``
    (dashes to underscores) — graftlint GL-CFG13 enforces the bijection."""
    g = p.add_argument_group(
        "frontend federation",
        "horizontal frontend scale-out: N frontends gossip membership and "
        "slice ownership, forward foreign-slice ops peer-to-peer, and "
        "replicate control state for HA (see docs/OPERATIONS.md "
        "\"Frontend scale-out & HA\")",
    )
    g.add_argument(
        "--frontend-seeds", default=None, metavar="H1:P1,H2:P2,...",
        help="comma-separated peer-plane seed addresses of any live "
        "frontends; arming this is the federation master switch (a node "
        "may seed itself harmlessly; default off)",
    )
    g.add_argument(
        "--frontend-advertise", default=None, metavar="HOST:PORT",
        help="peer address this frontend advertises to the federation "
        "(default: the bound host + an ephemeral peer port)",
    )
    g.add_argument(
        "--frontend-gossip-interval-s", default=None, metavar="DUR",
        help="gossip cadence: membership + slice-table deltas + budget "
        "shares to every live peer per tick (default 0.5s)",
    )
    g.add_argument(
        "--frontend-gossip-timeout-s", default=None, metavar="DUR",
        help="heartbeat age past which a peer is suspect — its slices "
        "park writes (429) until the link closes (promotion) or gossip "
        "resumes (default 3s)",
    )
    g.add_argument(
        "--frontend-replicate-every", type=int, default=None, metavar="N",
        help="flush the control-state dirty-row buffer to the standby "
        "peer once it holds N rows (interval flushes any remainder; "
        "default 16)",
    )
    g.add_argument(
        "--frontend-replicate-interval-s", default=None, metavar="DUR",
        help="control-state replication stream-pass cadence (default "
        "0.25s)",
    )


def _frontend_overrides(args: argparse.Namespace) -> dict:
    """``--frontend-*`` flags → SimulationConfig override kwargs."""
    return {
        "frontend_seeds": args.frontend_seeds,
        "frontend_advertise": args.frontend_advertise,
        "frontend_gossip_interval_s": (
            parse_duration(args.frontend_gossip_interval_s)
            if args.frontend_gossip_interval_s is not None
            else None
        ),
        "frontend_gossip_timeout_s": (
            parse_duration(args.frontend_gossip_timeout_s)
            if args.frontend_gossip_timeout_s is not None
            else None
        ),
        "frontend_replicate_every": args.frontend_replicate_every,
        "frontend_replicate_interval_s": (
            parse_duration(args.frontend_replicate_interval_s)
            if args.frontend_replicate_interval_s is not None
            else None
        ),
    }


def _add_chaos_net(p: argparse.ArgumentParser) -> None:
    """The network chaos plane's knobs (``runtime/netchaos.py``).  Every
    ``--chaos-net-X`` flag maps 1:1 onto ``NetworkChaosConfig.X`` (dashes to
    underscores; bare ``--chaos-net`` maps to ``enabled``) —
    ``tools/check_chaos_config.py`` lint-enforces the bijection."""
    g = p.add_argument_group(
        "network chaos",
        "seeded wire-fault injection: drops/delays/duplicates/reorders per "
        "message plus scheduled partitions with heal times; any flag below "
        "arms the plane (see docs/OPERATIONS.md \"Network chaos\")",
    )
    g.add_argument(
        "--chaos-net",
        action="store_true",
        default=None,
        help="arm the network chaos plane with config/default knobs",
    )
    g.add_argument("--chaos-net-seed", type=int, default=None, metavar="N")
    g.add_argument(
        "--chaos-net-drop-p", type=float, default=None, metavar="P",
        help="probability a sent message is silently dropped",
    )
    g.add_argument(
        "--chaos-net-delay-p", type=float, default=None, metavar="P",
        help="probability a sent message is delayed",
    )
    g.add_argument(
        "--chaos-net-delay-s", default=None, metavar="DUR",
        help="max injected latency per delayed message (e.g. 50ms)",
    )
    g.add_argument(
        "--chaos-net-duplicate-p", type=float, default=None, metavar="P",
        help="probability a sent message is sent twice",
    )
    g.add_argument(
        "--chaos-net-reorder-p", type=float, default=None, metavar="P",
        help="probability a sent message is overtaken by the next one",
    )
    g.add_argument(
        "--chaos-net-partition-after-s", default=None, metavar="DUR",
        help="first scheduled partition fires this long after start",
    )
    g.add_argument(
        "--chaos-net-partition-every-s", default=None, metavar="DUR",
        help="further partitions fire at this period",
    )
    g.add_argument(
        "--chaos-net-partition-heal-s", default=None, metavar="DUR",
        help="each partition heals after this long",
    )
    g.add_argument(
        "--chaos-net-max-partitions", type=int, default=None, metavar="N",
        help="partition budget (0 = probabilistic faults only)",
    )
    g.add_argument(
        "--chaos-net-scope",
        choices=["peer", "control", "all"],
        default=None,
        help="which planes the chaos wraps: the worker-to-worker data "
        "plane, the frontend-worker control plane, or both",
    )


def _chaos_net_overrides(args: argparse.Namespace) -> Optional[dict]:
    """``--chaos-net-*`` flags → a NetworkChaosConfig kwargs dict (None when
    no flag was given).  Any knob arms the plane; durations accept the
    config style ("50ms")."""
    out = {
        "seed": args.chaos_net_seed,
        "drop_p": args.chaos_net_drop_p,
        "delay_p": args.chaos_net_delay_p,
        "delay_s": (
            parse_duration(args.chaos_net_delay_s)
            if args.chaos_net_delay_s is not None
            else None
        ),
        "duplicate_p": args.chaos_net_duplicate_p,
        "reorder_p": args.chaos_net_reorder_p,
        "partition_after_s": (
            parse_duration(args.chaos_net_partition_after_s)
            if args.chaos_net_partition_after_s is not None
            else None
        ),
        "partition_every_s": (
            parse_duration(args.chaos_net_partition_every_s)
            if args.chaos_net_partition_every_s is not None
            else None
        ),
        "partition_heal_s": (
            parse_duration(args.chaos_net_partition_heal_s)
            if args.chaos_net_partition_heal_s is not None
            else None
        ),
        "max_partitions": args.chaos_net_max_partitions,
        "scope": args.chaos_net_scope,
    }
    out = {k: v for k, v in out.items() if v is not None}
    if not out and not args.chaos_net:
        return None
    out["enabled"] = True
    return out


def _parse_window(spec):
    """"Y0:Y1,X0:X1" → (y0, y1, x0, x1); None passes through."""
    if spec is None:
        return None
    try:
        rows, cols = spec.split(",")
        y0, y1 = (int(v) for v in rows.split(":"))
        x0, x1 = (int(v) for v in cols.split(":"))
    except ValueError:
        raise SystemExit(
            f"bad --probe-window {spec!r}; expected Y0:Y1,X0:X1 (e.g. 8:17,8:44)"
        )
    return (y0, y1, x0, x1)


def _overrides(args: argparse.Namespace) -> dict:
    mesh = None
    if args.mesh:
        rows, cols = args.mesh.lower().split("x")
        mesh = (int(rows), int(cols))
    out = {
        "rule": args.rule,
        "height": args.height,
        "width": args.width,
        "density": args.density,
        "seed": args.seed,
        "pattern": args.pattern,
        "max_epochs": args.max_epochs,
        "tick_s": parse_duration(args.tick) if args.tick is not None else None,
        "steps_per_call": args.steps_per_call,
        "kernel": args.kernel,
        "pallas_block_rows": args.pallas_block_rows,
        "pallas_vmem_limit_mb": args.pallas_vmem_limit_mb,
        "halo_width": args.halo_width,
        "mesh_shape": mesh,
        "backend": args.backend,
        "checkpoint_dir": args.checkpoint_dir,
        "checkpoint_every": args.checkpoint_every,
        "checkpoint_format": args.checkpoint_format,
        "checkpoint_async": False if args.checkpoint_sync else None,
        "render_every": args.render_every,
        "render_max_cells": args.render_max_cells,
        "probe_window": _parse_window(args.probe_window),
        "metrics_every": args.metrics_every,
        "metrics_file": args.metrics_file,
        "metrics_port": args.metrics_port,
        "log_events": args.log_events,
        "trace_file": args.trace_file,
        "flight_dir": args.flight_dir,
        "obs_defer": args.obs_defer,
        "obs_digest": args.obs_digest,
        **_obs_programs_overrides(args),
        "sparse_cluster": {"on": True, "off": False, None: None}[
            args.sparse_cluster
        ],
        "sparse_kernel": {"on": True, "off": False, None: None}[
            args.sparse_kernel
        ],
        "sparse_block": args.sparse_block,
        "sparse_threshold": args.sparse_threshold,
        **_ff_overrides(args),
        "log_file": args.log_file,
        "distributed": args.distributed,
        "coordinator_address": args.coordinator,
        "num_processes": args.num_processes,
        "process_id": args.process_id,
    }
    if args.inject_faults:
        out["fault_injection"] = {"enabled": True}
    return out


@contextlib.contextmanager
def _sigterm_as_interrupt():
    """Map SIGTERM to KeyboardInterrupt for the duration of a role's serve
    loop, so orchestrator stops share the ^C graceful-shutdown path.

    Main thread only; the previous handler is restored on every exit path.
    A C-installed handler (getsignal() → None) cannot be saved or
    re-installed through the signal module, so in that embedded case ours is
    never installed and SIGTERM behavior is untouched."""
    import signal as _signal

    def _handler(signum, frame):
        raise KeyboardInterrupt

    _NOT_INSTALLED = object()
    prev = _NOT_INSTALLED
    try:
        if _signal.getsignal(_signal.SIGTERM) is not None:
            prev = _signal.signal(_signal.SIGTERM, _handler)
    except ValueError:  # not the main thread (embedded use)
        pass
    try:
        yield
    finally:
        if prev is not _NOT_INSTALLED:
            _signal.signal(_signal.SIGTERM, prev)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="akka_game_of_life_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    run_p = sub.add_parser("run", help="standalone simulation on local devices")
    _add_common(run_p)
    run_p.add_argument(
        "--trace-dir",
        help="capture a jax.profiler trace of the run into this directory "
        "(view with TensorBoard/Perfetto)",
    )
    run_p.add_argument(
        "--dump-rle",
        metavar="PATH",
        help="write the final board as a Golly/LifeWiki .rle file "
        "(O(board) host fetch — meant for boards you would also render)",
    )
    run_p.add_argument(
        "--fast-forward",
        type=int,
        default=None,
        metavar="T",
        help="jump to epoch T up front via the O(log T) linear-rule fast "
        "path (ops/fastforward.py; XOR-linear rules only — refused loudly "
        "otherwise), then run the normal loop for any remaining "
        "--max-epochs.  T is an ABSOLUTE epoch like --max-epochs: a "
        "resumed run jumps only the remainder, so interrupted and "
        "uninterrupted runs land on the same trajectory; prints the "
        "landed epoch + digest",
    )

    fe_p = sub.add_parser("frontend", help="control-plane coordinator (RunFrontend)")
    _add_common(fe_p)
    fe_p.add_argument("--port", type=int, default=2551)
    fe_p.add_argument("--host", default="127.0.0.1")
    fe_p.add_argument("--wait-for-backends", default=None, help="e.g. 5s")
    fe_p.add_argument("--min-backends", type=int, default=1)
    fe_p.add_argument(
        "--exchange-width",
        type=int,
        default=None,
        help="boundary-ring width k: one peer exchange buys k local epochs "
        "per tile (communication-avoiding; cadences must be multiples of k)",
    )
    fe_p.add_argument(
        "--tiles-per-worker",
        type=int,
        default=None,
        help="tile oversubscription: each worker hosts this many tiles "
        "(default 1) — >1 gives the batched halo plane several rings per "
        "peer per epoch to coalesce",
    )
    _add_ring_plane(fe_p)
    _add_rebalance(fe_p)
    # The simulation frontend can ALSO host the serve plane (one cluster,
    # both products): --serve-cluster on mounts /boards on its obs port.
    _add_serve(fe_p)
    _add_frontend_federation(fe_p)
    _add_chaos_net(fe_p)

    sv_p = sub.add_parser(
        "serve",
        help="multi-tenant board service: vmapped batched boards behind "
        "a /boards HTTP API with admission control (mounted on the obs "
        "endpoint alongside /metrics, /healthz, /trace)",
    )
    sv_p.add_argument("--config", help="TOML or JSON config file")
    _add_platform(sv_p)
    sv_p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="HTTP port for /boards + /metrics + /healthz + /trace "
        "(default 0 = ephemeral, printed at startup)",
    )
    sv_p.add_argument(
        "--port", type=int, default=2551,
        help="cluster listener port workers join (--serve-cluster on)",
    )
    sv_p.add_argument("--host", default="127.0.0.1")
    sv_p.add_argument(
        "--min-backends", type=int, default=1,
        help="workers to wait for before serving (--serve-cluster on)",
    )
    _add_serve(sv_p)
    _add_frontend_federation(sv_p)
    _add_ff(sv_p)
    _add_obs_programs(sv_p)

    st_p = sub.add_parser(
        "selftest",
        help="verify this machine end-to-end: gun phase, oracle equivalence, "
        "checkpoint resume, chaos replay, sharding (the reference's manual "
        "procedure, automated)",
    )
    _add_platform(st_p)
    st_p.add_argument(
        "--kernel",
        choices=list(_KERNEL_CHOICES),
        default="auto",
        help="kernel the checks drive (default auto — what `run` would pick)",
    )

    sub.add_parser(
        "models",
        help="list the registered rule families (name, rulestring, kind, "
        "states, radius) as JSON lines",
    )

    tune_p = sub.add_parser(
        "tune",
        help="autotune the Pallas kernel's (block_rows, steps_per_sweep) on "
        "this device: one JSON line per measured point (best first), then "
        "the winning flags",
    )
    tune_p.add_argument("--size", type=int, default=65536)
    tune_p.add_argument("--steps-per-call", type=int, default=64)
    tune_p.add_argument("--blocks", default="64,128,192,256", metavar="B1,B2,...")
    tune_p.add_argument("--sweeps", default="4,8,16", metavar="K1,K2,...")
    tune_p.add_argument("--timed-calls", type=int, default=2)
    tune_p.add_argument("--vmem-limit-mb", type=int, default=0)
    tune_p.add_argument("--rule", default="conway")
    tune_p.add_argument("--interpret", action="store_true", help=argparse.SUPPRESS)
    _add_platform(tune_p)

    ck_p = sub.add_parser(
        "checkpoints",
        help="inspect a checkpoint directory: one JSON line per durable "
        "epoch (epoch, layout, rule, shape, bytes on disk)",
    )
    ck_p.add_argument("dir")
    ck_p.add_argument(
        "--validate",
        action="store_true",
        help="additionally load each epoch in full and report ok/error "
        "(exit 1 if any epoch fails)",
    )
    _add_platform(ck_p)

    be_p = sub.add_parser("backend", help="control-plane worker (RunBackend)")
    be_p.add_argument(
        "--config",
        help="TOML or JSON config file; the worker consumes its [net_chaos] "
        "block (share one file with the frontend so the drill is one "
        "coherent fault script) — flags below override it",
    )
    be_p.add_argument("--port", type=int, default=2551, help="frontend port to join")
    be_p.add_argument("--host", default="127.0.0.1")
    be_p.add_argument("--name", default=None)
    _add_platform(be_p)
    be_p.add_argument(
        "--engine",
        choices=["numpy", "jax", "swar", "actor", "actor-native"],
        default="jax",
        help="tile step engine: jax = jitted on local accelerator (TPU path), "
        "numpy = host-only parity path, swar = C++ 64-cells-per-word SWAR "
        "chunks (host machine code; binary rules), actor = per-cell actor "
        "engine (the reference's architecture, BASELINE config 1), "
        "actor-native = the same engine compiled to machine code (C++ via "
        "ctypes)",
    )
    be_p.add_argument(
        "--metrics-file",
        help="dump this worker's Prometheus exposition here every few "
        "seconds and on exit (the worker's peer/data-plane counters live "
        "in this process, not the frontend's)",
    )
    be_p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="serve this worker's live /metrics + /healthz on this port "
        "(0 = off)",
    )
    be_p.add_argument(
        "--log-events",
        metavar="PATH",
        help="append worker-labeled JSONL lifecycle events here",
    )
    be_p.add_argument(
        "--trace-file",
        metavar="PATH",
        help="write this worker's span buffer as Perfetto JSON on exit "
        "(same trace ids as the frontend's — merge the files by trace_id)",
    )
    be_p.add_argument(
        "--flight-dir",
        metavar="DIR",
        default="artifacts",
        help="directory for this worker's flight-recorder crash dumps "
        "(default: artifacts; empty string disables)",
    )
    _add_chaos_net(be_p)
    be_p.add_argument(
        "--pallas",
        choices=["auto", "off", "interpret"],
        default=None,
        help="jax-engine Mosaic pin: auto (default) steps binary chunks "
        "through the Pallas sweep on a real single-TPU worker (XLA-scan "
        "demotion if Mosaic fails), off pins the XLA scan, interpret "
        "forces the sweep CPU-side (testing)",
    )

    args = parser.parse_args(argv)
    _apply_platform(getattr(args, "platform", None))

    if args.command == "run":
        cfg = load_config(args.config, _overrides(args))
        if args.dump_rle:
            # Fail BEFORE the run, not after hours of compute: RLE's
            # multi-state alphabet stops at state 24 (encode_rle raises),
            # and an unwritable path would lose the board at the very end.
            from akka_game_of_life_tpu.ops.rules import resolve_rule

            states = resolve_rule(cfg.rule).states
            if states - 1 > 24:
                raise SystemExit(
                    f"--dump-rle: rule {cfg.rule!r} has {states} states; "
                    "RLE's alphabet stops at 24 (25 states incl. dead)"
                )
            try:
                with open(args.dump_rle, "a", encoding="utf-8"):
                    pass
            except OSError as e:
                raise SystemExit(f"--dump-rle: cannot write {args.dump_rle!r}: {e}")
        from akka_game_of_life_tpu.runtime.simulation import Simulation

        if cfg.max_epochs is None:
            cfg.max_epochs = 100
        sim = Simulation(cfg)

        # SIGTERM order matters: the interrupt mapping installs first, the
        # flight dump wraps it — an orchestrator stop dumps the span/event
        # ring, THEN follows the graceful KeyboardInterrupt path.
        from akka_game_of_life_tpu.runtime.signals import flight_dump_on_signals

        with _sigterm_as_interrupt(), flight_dump_on_signals(
            sim.tracer.flight
        ), _metrics_endpoint(cfg, sim):
            try:
                return _run_simulation(args, cfg, sim)
            except KeyboardInterrupt:
                # Signal landed outside advance()'s graceful window (startup
                # compile, summary, epilogue): exit 130 without a save — the
                # cadence checkpoints are the durable state.
                print(
                    f"interrupted outside the run loop at epoch {sim.epoch}",
                    file=sys.stderr,
                    flush=True,
                )
                return 130

    if args.command == "frontend":
        overrides = _overrides(args)
        overrides.update(
            role="frontend",
            host=args.host,
            port=args.port,
            exchange_width=args.exchange_width,
            tiles_per_worker=args.tiles_per_worker,
            **_ring_plane_overrides(args),
            **_rebalance_overrides(args),
            **_serve_overrides(args),
            **_frontend_overrides(args),
            wait_for_backends_s=(
                parse_duration(args.wait_for_backends)
                if args.wait_for_backends is not None
                else None
            ),
            net_chaos=_chaos_net_overrides(args),
        )
        cfg = load_config(args.config, overrides)
        try:
            from akka_game_of_life_tpu.runtime.frontend import run_frontend
        except ImportError as e:  # pragma: no cover
            raise SystemExit(f"frontend role unavailable: {e}")

        from akka_game_of_life_tpu.obs import get_tracer
        from akka_game_of_life_tpu.runtime.signals import flight_dump_on_signals

        with _sigterm_as_interrupt(), flight_dump_on_signals(
            get_tracer().flight
        ):
            try:
                return run_frontend(cfg, min_backends=args.min_backends)
            except KeyboardInterrupt:
                # run_frontend handles interrupts inside its serve loop; this
                # covers startup (bind/quorum/deploy) windows.
                return 130

    if args.command == "serve":
        cfg = load_config(
            args.config,
            {
                "role": "serve",
                "metrics_port": args.metrics_port,
                "host": args.host,
                "port": args.port,
                **_serve_overrides(args),
                **_frontend_overrides(args),
                **_ff_overrides(args),
                **_obs_programs_overrides(args),
            },
        )
        from akka_game_of_life_tpu.obs import get_tracer
        from akka_game_of_life_tpu.runtime.signals import flight_dump_on_signals

        if cfg.serve_cluster:
            # Cluster-sharded mode: this process is a serve-only cluster
            # frontend; workers join with the ordinary `backend` role and
            # each hosts session shards in its own batch engine.
            from akka_game_of_life_tpu.serve.cluster import run_serve_cluster

            with _sigterm_as_interrupt(), flight_dump_on_signals(
                get_tracer().flight
            ):
                try:
                    return run_serve_cluster(
                        cfg, min_backends=args.min_backends
                    )
                except KeyboardInterrupt:
                    return 130
        from akka_game_of_life_tpu.serve.api import run_serve

        with _sigterm_as_interrupt(), flight_dump_on_signals(
            get_tracer().flight
        ):
            try:
                return run_serve(cfg)
            except KeyboardInterrupt:
                # run_serve handles interrupts in its wait loop; this
                # covers the bind/startup window.
                return 130

    return _other_commands(args)


@contextlib.contextmanager
def _metrics_endpoint(cfg, sim):
    """Live /metrics + /healthz for the standalone role while the run body
    executes (the frontend role starts its own in Frontend.start)."""
    import jax

    if not cfg.metrics_port or jax.process_index() != 0:
        yield
        return
    from akka_game_of_life_tpu.obs import MetricsServer
    from akka_game_of_life_tpu.obs.programs import get_programs, http_routes
    from akka_game_of_life_tpu.runtime.profiling import ProfilerCapture

    programs = get_programs().configure(
        node="standalone",
        metrics=sim.metrics,
        enabled=cfg.obs_programs,
    )
    profiler = ProfilerCapture(
        cfg.flight_dir or "artifacts",
        node="standalone",
        max_seconds=cfg.obs_profile_max_s,
        min_interval_s=cfg.obs_profile_min_interval_s,
    )
    server = MetricsServer(
        sim.metrics,
        port=cfg.metrics_port,
        health=lambda: {"ok": True, "epoch": sim.epoch},
        tracer=sim.tracer,
        routes=http_routes(registry=programs, profile=profiler.capture),
    )
    print(
        f"metrics on :{server.port}/metrics "
        f"(+/healthz,/trace,/programs,/cost,/profile)",
        flush=True,
    )
    try:
        yield
    finally:
        server.close()


def _run_simulation(args, cfg, sim) -> int:
    """The `run` body between SIGTERM-handler install and restore."""
    from akka_game_of_life_tpu.runtime import profiling

    interrupted = False
    with sim, profiling.trace(args.trace_dir):
        # --max-epochs is the absolute end epoch: a resumed run (from a
        # checkpoint at epoch E) advances the remaining max_epochs - E.
        try:
            if getattr(args, "fast_forward", None):
                from akka_game_of_life_tpu.ops.digest import format_digest

                # Absolute target, like --max-epochs: a resumed run (from
                # a checkpoint at epoch E) jumps only the remaining
                # fast_forward - E, never re-applies the whole span.
                try:
                    ep = sim.fast_forward(
                        max(0, args.fast_forward - sim.epoch)
                    )
                except ValueError as e:
                    # Predictable operator misuse (non-linear rule, ff
                    # disabled, actor backend): one line, not a traceback.
                    raise SystemExit(f"--fast-forward: {e}")
                print(
                    f"fast-forwarded to epoch {ep}: "
                    f"digest={format_digest(sim.board_digest())}",
                    file=sim.observer.out,
                    flush=True,
                )
            sim.advance(max(0, cfg.max_epochs - sim.epoch))
        except KeyboardInterrupt:
            # Graceful ^C: the board is consistent at the last completed
            # chunk; make it durable so the run is resumable from HERE
            # rather than the last cadence point.  (The reference's
            # Pause/Resume protocol was dead code, Run.scala had no
            # shutdown path at all; this is the standalone analog of the
            # cluster frontend's pause+checkpoint.)
            interrupted = True
            import jax

            if sim.store is not None and jax.process_count() == 1:
                # Multi-host runs are excluded: checkpoint() is a
                # collective + barrier the uninterrupted ranks never
                # enter, so it would hang, not save.  Masked so a second
                # signal cannot abort the save it was promised.
                from akka_game_of_life_tpu.runtime.signals import mask_interrupts

                with mask_interrupts():
                    sim.checkpoint()
                    sim.flush()
                print(
                    f"interrupted at epoch {sim.epoch}; checkpoint written",
                    file=sys.stderr,
                    flush=True,
                )
            else:
                print(
                    f"interrupted at epoch {sim.epoch} (no durable save: "
                    + (
                        "multi-host run"
                        if sim.store is not None
                        else "no checkpoint dir"
                    )
                    + ")",
                    file=sys.stderr,
                    flush=True,
                )
        stats = sim.observer.summary()
        if stats is not None:
            import json as _json

            # Inside the with block so the line reaches the observer's
            # sink (e.g. --log-file) before close(); out is stdout by
            # default.
            print(
                "run summary: "
                + _json.dumps(
                    {"kernel": sim.kernel, "epoch": sim.epoch, **stats}
                ),
                file=sim.observer.out,
                flush=True,
            )
    # End-of-run device-memory watermarks: exported as the cataloged
    # per-device gauges (so a --metrics-file final dump carries them even
    # when the run never hit a metrics cadence), printed under --trace-dir
    # as before.
    from akka_game_of_life_tpu.obs.programs import get_programs

    try:
        final_dev_stats = get_programs().refresh_device_gauges()
    except Exception:  # noqa: BLE001 — observability must not fail the run
        final_dev_stats = {}
    if args.trace_dir:
        for dev, stats in final_dev_stats.items():
            print(f"[profile] {dev}: {stats}", flush=True)
    # board_host() is an O(board) collective in multi-host runs — every
    # rank calls it, at most once, shared by the dump and the fallback
    # render; only rank 0 writes/prints.  An interrupted run skips the
    # whole epilogue: the checkpoint already preserves the state, and a
    # minutes-long fetch after ^C invites a second ^C mid-write.
    final = None
    if args.dump_rle and not interrupted:
        from akka_game_of_life_tpu.ops.rules import resolve_rule
        from akka_game_of_life_tpu.utils.patterns import encode_rle

        final = sim.board_host()
        import jax

        if jax.process_index() == 0:
            with open(args.dump_rle, "w", encoding="utf-8") as f:
                f.write(encode_rle(final, resolve_rule(cfg.rule).rulestring()))
            print(f"wrote {args.dump_rle}", flush=True)
    if cfg.render_every == 0 and cfg.metrics_every == 0 and not interrupted:
        # Always show something at the end, like the reference's info.log.
        from akka_game_of_life_tpu.runtime.render import render_ascii

        if final is None:
            final = sim.board_host()
        import jax

        if jax.process_index() == 0:
            print(f"epoch {sim.epoch}:")
            print(render_ascii(final, cfg.render_max_cells))
    return 130 if interrupted else 0


def _other_commands(args) -> int:
    """Dispatch for the non-run, non-frontend subcommands."""
    if args.command == "tune":
        import json

        from akka_game_of_life_tpu.runtime.autotune import (
            best_flags,
            best_point,
            sweep,
        )

        results = sweep(
            args.size,
            steps_per_call=args.steps_per_call,
            blocks=[int(v) for v in args.blocks.split(",")],
            sweeps=[int(v) for v in args.sweeps.split(",")],
            timed_calls=args.timed_calls,
            vmem_limit_mb=args.vmem_limit_mb,
            interpret=args.interpret,
            rule=args.rule,
        )
        for p in results:
            print(json.dumps(p), flush=True)
        flags = best_flags(results, rule=args.rule)
        if flags is None:
            print("no feasible point succeeded", file=sys.stderr)
            return 1
        # Machine-readable summary line: what a harvest script (or the
        # MEASURED_BLOCK_ROWS_CAPS table update) greps out of an archived
        # tune log without re-parsing the per-point lines above.  best_point
        # is the same selection best_flags rendered, so the two cannot
        # drift apart.
        best = best_point(results)
        print(
            json.dumps(
                {
                    "tune": {"size": args.size, "rule": args.rule},
                    "best": best,
                    "flags": flags,
                }
            ),
            flush=True,
        )
        print(f"best: {flags}")
        return 0

    if args.command == "checkpoints":
        import json

        from akka_game_of_life_tpu.runtime.checkpoint import describe_store

        n = failed = 0
        for info in describe_store(args.dir, validate=args.validate):
            print(json.dumps(info), flush=True)
            n += 1
            # Unreadable metadata fails the health check even without
            # --validate; ok=False only exists when --validate ran.
            failed += ("error" in info) or (info.get("ok") is False)
        if n == 0:
            print(f"no checkpoints found in {args.dir}", file=sys.stderr)
            return 1
        return 1 if failed else 0

    if args.command == "models":
        import json

        from akka_game_of_life_tpu.ops.rules import NAMED_RULES

        for name in sorted(NAMED_RULES):
            r = NAMED_RULES[name]
            print(
                json.dumps(
                    {
                        "name": name,
                        "rulestring": r.rulestring(),
                        "kind": r.kind,
                        "states": r.states,
                        "radius": r.radius,
                        "neighborhood": r.neighborhood,
                    }
                )
            )
        return 0

    if args.command == "selftest":
        from akka_game_of_life_tpu.runtime.selftest import run_selftest

        return 1 if run_selftest(kernel=args.kernel) else 0

    if args.command == "backend":
        try:
            from akka_game_of_life_tpu.runtime.backend import run_backend
        except ImportError as e:  # pragma: no cover
            raise SystemExit(f"backend role unavailable: {e}")

        from akka_game_of_life_tpu.obs import get_tracer
        from akka_game_of_life_tpu.runtime.signals import flight_dump_on_signals

        # The worker's chaos policy layers exactly like the frontend's:
        # config-file [net_chaos] block < --chaos-net-* flags.
        chaos_kwargs = _chaos_net_overrides(args)
        if args.config or chaos_kwargs is not None:
            cfg = load_config(
                args.config,
                {"net_chaos": chaos_kwargs} if chaos_kwargs else None,
            )
            chaos_cfg = cfg.net_chaos if cfg.net_chaos.enabled else None
        else:
            chaos_cfg = None
        with _sigterm_as_interrupt(), flight_dump_on_signals(
            get_tracer().flight
        ):
            try:
                return run_backend(
                    host=args.host,
                    port=args.port,
                    name=args.name,
                    engine=args.engine,
                    pallas=args.pallas,
                    metrics_file=args.metrics_file,
                    metrics_port=args.metrics_port,
                    log_events=args.log_events,
                    trace_file=args.trace_file,
                    flight_dir=args.flight_dir,
                    net_chaos=chaos_cfg,
                )
            except KeyboardInterrupt:
                # run_backend handles interrupts inside its serve loop; this
                # covers the connect/join window.
                return 130

    return 2


if __name__ == "__main__":
    sys.exit(main())
