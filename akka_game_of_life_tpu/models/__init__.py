from akka_game_of_life_tpu.models.registry import (  # noqa: F401
    CAModel,
    get_model,
    list_models,
)
