"""Model registry: the CA families the framework ships.

The reference supports exactly one hard-coded (and buggy) rule
(``NextStateCellGathererActor.scala:44``).  Here each "model" is a rule plus
its execution profile; all BASELINE.json benchmark configs are registered:

- ``conway``           — Conway B3/S23 (configs 1, 2, 5)
- ``highlife``         — HighLife B36/S23 (config 3)
- ``day-and-night``    — Day & Night B3678/S34678 (config 3)
- ``brians-brain``     — Brian's Brain /2/3, int8 Generations state (config 4)
- ``wireworld``        — WireWorld, the non-totalistic 4-state digital-logic
                         CA (``Rule.kind="wireworld"``; dense kernels + actor
                         engines per-cell, bit-plane SWAR packed — 2
                         bits/cell through ``ops/bitpack_gen``)
- ``bugs``             — Larger-than-Life (Evans), radius-5 Moore; counts run
                         as separable shift-add window sums (``ops/ltl.py``);
                         any ``"R<r>,B<ranges>,S<ranges>"`` rulestring works
- plus seeds, life-without-death, star-wars, and any rulestring on demand.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import numpy as np

from akka_game_of_life_tpu.ops import stencil
from akka_game_of_life_tpu.ops.rules import NAMED_RULES, Rule, resolve_rule
from akka_game_of_life_tpu.utils.patterns import random_grid


@dataclasses.dataclass(frozen=True)
class CAModel:
    """A cellular-automaton model: rule + init + step.

    ``step``/``run`` are jitted closures over the rule (compiled once per rule
    and step count); ``init`` produces a host-side numpy board so placement and
    pattern stamping stay off the device path.
    """

    rule: Rule

    @property
    def name(self) -> str:
        return str(self.rule)

    @property
    def dtype(self):
        return stencil.STATE_DTYPE

    def init(
        self,
        shape: Tuple[int, int],
        *,
        density: float = 0.5,
        seed: int = 0,
    ) -> np.ndarray:
        return random_grid(shape, density=density, seed=seed, states=self.rule.states)

    @property
    def step(self) -> Callable[[jax.Array], jax.Array]:
        return stencil.step_fn(self.rule)

    def run(self, n_steps: int) -> Callable[[jax.Array], jax.Array]:
        return stencil.multi_step_fn(self.rule, n_steps)


def get_model(spec) -> CAModel:
    """Build a model from a Rule, a registered name, or any rulestring."""
    return CAModel(rule=resolve_rule(spec))


def list_models() -> Tuple[str, ...]:
    return tuple(sorted(NAMED_RULES))
