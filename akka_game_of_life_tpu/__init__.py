"""akka_game_of_life_tpu — a TPU-native distributed cellular-automaton framework.

A ground-up re-architecture of the capabilities of the reference
``almendar/akka-game-of-life`` (a distributed Conway's-Game-of-Life simulator
on Akka Cluster, see ``/root/reference``):

- the reference's one-actor-per-cell compute layer (``CellActor.scala`` +
  ``NextStateCellGathererActor.scala``) collapses into jitted dense stencil
  kernels over HBM-resident grid arrays (:mod:`akka_game_of_life_tpu.ops`);
- its Akka-remoting neighbor messages become ``lax.ppermute`` halo exchanges
  over a 2-D ``jax.sharding.Mesh`` (:mod:`akka_game_of_life_tpu.parallel`);
- its distributed-systems capabilities — cluster roles, membership, tick-driven
  epochs, fault injection, crash recovery with replay, node-loss redeployment,
  epoch-synchronized rendering (``BoardCreator.scala``, ``Run.scala``,
  ``LoggerActor.scala``) — are rebuilt as a thin host-side control plane with
  real checkpoint/resume (:mod:`akka_game_of_life_tpu.runtime`).

The per-cell ``Tick``/``CellState`` message protocol of the reference survives
as the plugin boundary between the CPU per-cell backend and the TPU stencil
backend (:mod:`akka_game_of_life_tpu.runtime.protocol`).
"""

__version__ = "0.1.0"

from akka_game_of_life_tpu.ops.rules import Rule, parse_rule  # noqa: F401
from akka_game_of_life_tpu.models.registry import get_model, list_models  # noqa: F401


def __getattr__(name):  # lazy: keep `import akka_game_of_life_tpu` light
    if name == "Simulation":
        from akka_game_of_life_tpu.runtime.simulation import Simulation

        return Simulation
    if name == "SimulationConfig":
        from akka_game_of_life_tpu.runtime.config import SimulationConfig

        return SimulationConfig
    if name == "cluster":
        from akka_game_of_life_tpu.runtime.harness import cluster

        return cluster
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
