"""Epoch-tagged boundary-ring store with pull semantics and bounded history.

This reproduces the reference's neighbor-state exchange contract
(``CellActor.scala:71-88``) at tile granularity:

- workers *push* their boundary ring after computing each epoch (the analog
  of a cell's state landing in its ``History`` map);
- workers *pull* the assembled halo for an epoch; a pull for an epoch whose
  neighbor rings haven't all arrived is **queued** and answered when the last
  ring lands — exactly the reference's request queue for not-yet-computed
  epochs (``CellActor.scala:75-77,82-88``);
- history is **bounded**: rings older than the last durable checkpoint are
  pruned (the reference's histories grow forever — SURVEY.md §2 bug 5 — and
  here the checkpoint, not an unbounded log, is the replay source).

Assembly: a tile's halo at epoch E needs its 8 tile-torus neighbors' rings at
E — edge rows/cols from the 4 axis neighbors, single corner cells from the 4
diagonals (the corner-propagation job that the sharded data plane solves with
its two-phase ppermute)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.runtime.tiles import Ring, TileId, TileLayout


class Halo:
    """The assembled width-k halo for a tile: four edge blocks incl. corners.

    k=1 is the reference's per-epoch exchange; k>1 is the communication-
    avoiding contract — one assembled halo licenses k local steps (the outer
    garbage front advances one cell per step, so the (h, w) interior of the
    padded slab stays exact through step k)."""

    def __init__(self, top: np.ndarray, bottom: np.ndarray, left: np.ndarray, right: np.ndarray):
        self.top = top  # (k, w+2k)
        self.bottom = bottom  # (k, w+2k)
        self.left = left  # (h, k)
        self.right = right  # (h, k)

    @property
    def width(self) -> int:
        return len(self.top)

    def pad(self, tile: np.ndarray) -> np.ndarray:
        """(h, w) tile → (h+2k, w+2k) halo-padded array."""
        k = self.width
        h, w = tile.shape
        out = np.empty((h + 2 * k, w + 2 * k), dtype=tile.dtype)
        out[k : h + k, k : w + k] = tile
        out[:k, :] = self.top
        out[h + k :, :] = self.bottom
        out[k : h + k, :k] = self.left
        out[k : h + k, w + k :] = self.right
        return out


def halos_equal(a: Optional[Halo], b: Optional[Halo]) -> bool:
    """Exact equality of two assembled halos — the quiescence tier's
    neighborhood-unchanged test (O(perimeter); cheap enough to run every
    chunk, and the first thing checked so active tiles never pay an
    O(tile) state compare)."""
    if a is None or b is None:
        return False
    return (
        np.array_equal(a.top, b.top)
        and np.array_equal(a.bottom, b.bottom)
        and np.array_equal(a.left, b.left)
        and np.array_equal(a.right, b.right)
    )


class BoundaryStore:
    """Thread-safe ring store + halo assembler + pending-pull queue."""

    def __init__(self, layout: TileLayout, width: int = 1) -> None:
        th, tw = layout.tile_shape
        if width < 1 or th < width or tw < width:
            raise ValueError(
                f"ring width {width} infeasible for tile shape {(th, tw)}"
            )
        self.layout = layout
        self.width = width
        self._rings: Dict[Tuple[TileId, int], Ring] = {}  # graftlint: guarded-by _lock
        self._pending: Dict[Tuple[TileId, int], List[Callable[[Halo], None]]] = {}  # graftlint: guarded-by _lock
        self._lock = threading.Lock()

    def push_ring(self, tile: TileId, epoch: int, ring: Ring) -> None:
        """Store a ring; answer any queued pulls it completes."""
        self.push_rings([(tile, epoch, ring)])

    def push_rings(self, items: List[Tuple[TileId, int, Ring]]) -> None:
        """Store a whole batch of rings under ONE lock acquisition, then
        answer the queued pulls the batch completes.  Callbacks fire only
        after every ring of the batch is stored: a coalesced PEER_RING_BATCH
        unblocks all its dependent tiles at once, so their steps (and the
        outbound rings those produce) run back-to-back — which is exactly
        what lets the sender's next batch fill up."""
        ready: List[Tuple[Callable[[Halo], None], Halo]] = []
        with self._lock:
            epochs = set()
            for tile, epoch, ring in items:
                self._rings[(tile, epoch)] = ring
                epochs.add(epoch)
            for (want_tile, want_epoch), callbacks in list(self._pending.items()):
                if want_epoch not in epochs:
                    continue
                halo = self._assemble_locked(want_tile, want_epoch)
                if halo is not None:
                    for cb in callbacks:
                        ready.append((cb, halo))
                    del self._pending[(want_tile, want_epoch)]
        for cb, halo in ready:
            cb(halo)

    def pull_halo_now(
        self, tile: TileId, epoch: int, callback: Callable[[Halo], None]
    ) -> Optional[Halo]:
        """Return the halo if assemblable right now; otherwise queue
        ``callback`` for when the last ring lands and return None.  Lets a
        caller catching up over many epochs consume ready halos in a loop
        instead of recursing through callbacks."""
        with self._lock:
            halo = self._assemble_locked(tile, epoch)
            if halo is None:
                self._pending.setdefault((tile, epoch), []).append(callback)
            return halo

    def _assemble_locked(self, tile: TileId, epoch: int) -> Optional[Halo]:
        nb = self.layout.neighbors(tile)
        rings = {}
        for direction, ntile in nb.items():
            ring = self._rings.get((ntile, epoch))
            if ring is None:
                return None
            rings[direction] = ring
        h, w = self.layout.tile_shape
        k = self.width
        top = np.empty((k, w + 2 * k), dtype=np.uint8)
        top[:, :k] = rings["nw"].corners["se"]
        top[:, k : w + k] = rings["n"].bottom
        top[:, w + k :] = rings["ne"].corners["sw"]
        bottom = np.empty((k, w + 2 * k), dtype=np.uint8)
        bottom[:, :k] = rings["sw"].corners["ne"]
        bottom[:, k : w + k] = rings["s"].top
        bottom[:, w + k :] = rings["se"].corners["nw"]
        left = np.asarray(rings["w"].right, dtype=np.uint8)
        right = np.asarray(rings["e"].left, dtype=np.uint8)
        return Halo(top, bottom, left, right)

    def missing_neighbor_rings(self, tile: TileId, epoch: int) -> List[TileId]:
        """Which of a tile's 8 neighbors have no stored ring at ``epoch`` —
        the re-ask targets for a stale pull."""
        with self._lock:
            return sorted(
                {
                    ntile
                    for ntile in self.layout.neighbors(tile).values()
                    if (ntile, epoch) not in self._rings
                }
            )

    def ring_at(self, tile: TileId, epoch: int):
        """The stored ring of ``tile`` at exactly ``epoch``, or None.  The
        resolution target of a quiescent peer's "same-ring" marker: the
        marker names the epoch whose ring bytes it repeats, and this lookup
        turns it back into the Ring without any wire payload."""
        with self._lock:
            return self._rings.get((tile, epoch))

    def ring_count(self) -> int:
        with self._lock:
            return len(self._rings)

    def rings_from(
        self, tile: TileId, epoch: int, limit: int = 256
    ) -> List[Tuple[int, Ring]]:
        """All stored rings of ``tile`` at epochs >= ``epoch`` (ascending,
        bounded).  A PEER_PULL reply streams these so a replaying neighbor
        catches up without one round-trip per epoch."""
        with self._lock:
            eps = sorted(e for (t, e) in self._rings if t == tile and e >= epoch)
            return [(e, self._rings[(tile, e)]) for e in eps[:limit]]

    def prune_below(self, epoch: int) -> int:
        """Drop rings for epochs < epoch (called after a durable checkpoint).
        Returns how many were dropped."""
        with self._lock:
            stale = [k for k in self._rings if k[1] < epoch]
            for k in stale:
                del self._rings[k]
            return len(stale)

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    def drop_pending_for_owner(self, tiles: List[TileId]) -> None:
        """Forget queued pulls from tiles being re-deployed (their new owner
        will re-pull)."""
        with self._lock:
            for key in [k for k in self._pending if k[0] in tiles]:
                del self._pending[key]
